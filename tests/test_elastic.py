"""Elastic worker pools + durable coordinator: autoscaling on
preemptible capacity with crash-resumable query state.

Reference parity: Presto's disaggregated-coordinator direction (elastic
membership, recoverable coordinator state — PAPER.md L3) on top of the
PR 5 substrate (spooled exchange, drain protocol, retry policies).
Chaos acceptance: under concurrent TPC-H load, (a) draining half the
worker pool and restoring it and (b) killing and restarting the
coordinator with queries queued both complete with ZERO failed queries;
the restarted coordinator resumes journaled queued queries without
client resubmission (``coordinator.resumed_queries`` asserted), and the
autoscaler scales up on queue depth and drains back down with no
flapping.
"""

import os
import threading
import time

import pytest

from presto_tpu.server import (
    CoordinatorServer,
    PrestoTpuClient,
    WorkerServer,
)
from presto_tpu.server import rpc
from presto_tpu.server.journal import CoordinatorJournal
from presto_tpu.server.launcher import LocalWorkerPoolProvider
from presto_tpu.server.pool import Autoscaler, WorkerPoolProvider
from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY


#: the multi-stage shuffle shape (producer + merge stages) the
#: placement and pool-halving tests exercise
JOIN_SQL = (
    "select o_orderpriority, count(*) as n "
    "from tpch.tiny.orders, tpch.tiny.lineitem "
    "where o_orderkey = l_orderkey "
    "group by o_orderpriority order by o_orderpriority"
)


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


def _mk_cluster(tmp_path, n=2, policy="TASK", extra=None, preemptible=()):
    cfg = {
        "exchange.spool-path": str(tmp_path / "spool"),
        "exchange.spool-bytes": "64MB",
    }
    cfg.update(extra or {})
    coord = CoordinatorServer(config=NodeConfig(dict(cfg))).start()
    coord.local.session.set("retry_policy", policy)
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri,
            config=NodeConfig(dict(cfg)),
            preemptible=(i in preemptible),
        ).start()
        for i in range(n)
    ]
    _wait(
        lambda: len(coord.active_workers()) >= n,
        msg="worker discovery",
    )
    return coord, workers


def _teardown(coord, workers):
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def _expected_rows(coord, sql):
    return [tuple(r) for r in coord.local.execute(sql).rows()]


# ------------------------------------------------------- journal unit


def test_journal_roundtrip_and_replay(tmp_path):
    j = CoordinatorJournal(str(tmp_path / "j"))
    j.record_submit("q_c1_aaa", "select 1", "alice", {"p": "select ?"})
    j.record_submit("q_c2_aaa", "select 2", "bob")
    j.record_prepare("s1", "select c from t where x = ?")
    j.record_finish("q_c1_aaa", "FINISHED")
    j.record_deallocate("nope")  # unknown: no-op
    # a fresh instance replays only the open query + live registry
    j2 = CoordinatorJournal(str(tmp_path / "j"))
    state = j2.replay()
    assert [r["qid"] for r in state.open] == ["q_c2_aaa"]
    assert state.open[0]["sql"] == "select 2"
    assert state.open[0]["user"] == "bob"
    assert state.prepared == {"s1": "select c from t where x = ?"}
    # closing the survivor empties the next replay
    j2.record_finish("q_c2_aaa", "RESUMED")
    assert CoordinatorJournal(str(tmp_path / "j")).replay().open == []
    # injected io_error on an append: the journal degrades to
    # best-effort (a full disk never fails admission) — that frame is
    # lost, but every frame that did land still replays
    from presto_tpu.utils import faults

    faults.configure(
        {"rules": [{"action": "io_error", "path": "journal-", "op": "write"}]}
    )
    try:
        j2.record_submit("q_c3_aaa", "select 3")
    finally:
        faults.configure(None)
    assert CoordinatorJournal(str(tmp_path / "j")).replay().open == []
    j2.record_submit("q_c4_aaa", "select 4")
    assert [
        r["qid"]
        for r in CoordinatorJournal(str(tmp_path / "j")).replay().open
    ] == ["q_c4_aaa"]


def test_journal_torn_and_corrupt_line_tolerance(tmp_path):
    path = tmp_path / "j"
    j = CoordinatorJournal(str(path))
    j.record_submit("q_c1_aaa", "select 1")
    j.record_submit("q_c2_aaa", "select 2")
    seg = sorted(path.glob("journal-*.jsonl"))[-1]
    raw = seg.read_text().splitlines()
    # torn tail (crash mid-append), a bit-flipped frame, and foreign
    # garbage must all be skipped at replay — never a crash
    flipped = raw[1][:12] + ("X" if raw[1][12] != "X" else "Y") + raw[1][13:]
    seg.write_text(
        "\n".join([raw[0], flipped, "not a frame", raw[1][: len(raw[1]) // 2]])
        + "\n"
    )
    before = REGISTRY.counter("journal.corrupt_lines").total
    state = CoordinatorJournal(str(path)).replay()
    assert [r["qid"] for r in state.open] == ["q_c1_aaa"]
    assert REGISTRY.counter("journal.corrupt_lines").total >= before + 3


def test_journal_checkpoint_compaction_bounds_segments(tmp_path):
    path = tmp_path / "j"
    j = CoordinatorJournal(str(path), segment_lines=4)
    # a long-running coordinator: many queries come and go, one stays
    j.record_submit("q_keep", "select 'keep'")
    for i in range(40):
        j.record_submit(f"q_{i}", f"select {i}")
        j.record_finish(f"q_{i}")
    segs = sorted(path.glob("journal-*.jsonl"))
    assert len(segs) <= 2, [s.name for s in segs]
    # the checkpoint kept the long-lived open query replayable even
    # though its submit frame's segment was GC'd long ago
    state = CoordinatorJournal(str(path)).replay()
    assert [r["qid"] for r in state.open] == ["q_keep"]


# --------------------------------------------- coordinator HA (restart)


def test_coordinator_restart_resumes_queued_queries(tmp_path):
    """THE coordinator-HA acceptance: kill a coordinator with queries
    QUEUED; the restarted coordinator (same journal, same port) resumes
    them from the journal without client resubmission — asserted via
    coordinator.resumed_queries — and the old statement ids stay
    routable through the restart alias."""
    cfg = NodeConfig({"coordinator.journal-path": str(tmp_path / "jr")})
    c1 = CoordinatorServer(config=cfg).start()
    # hold every admission slot: submissions stay QUEUED
    for _ in range(4):
        c1._admit.acquire()
    qs = [
        c1.submit("select count(*) as c from tpch.tiny.region")
        for _ in range(3)
    ]
    assert all(q.state == "QUEUED" for q in qs)
    port = c1.port
    before = REGISTRY.counter("coordinator.resumed_queries").total
    pool_before = REGISTRY.counter("pool.resumed_queries").total
    c1.shutdown()  # the bounce: queued queries would be forgotten

    c2 = CoordinatorServer(port=port, config=cfg).start()
    try:
        assert c2.resumed_queries == 3
        assert (
            REGISTRY.counter("coordinator.resumed_queries").total
            == before + 3
        )
        assert (
            REGISTRY.counter("pool.resumed_queries").total
            == pool_before + 3
        )
        for q in qs:
            rq = c2.lookup_query(q.qid)  # old id -> resumed run
            assert rq is not None
            assert rq.done.wait(60)
            assert rq.state == "FINISHED", rq.error
            assert rq.rows == [[5]]
        # every resumed query finished: a THIRD boot resumes nothing
        c3 = CoordinatorServer(config=cfg).start()
        assert c3.resumed_queries == 0
        c3.shutdown()
    finally:
        c2.shutdown()


def test_statement_ids_survive_a_second_bounce(tmp_path):
    """Review finding: the restart alias must be DURABLE — a client URI
    minted two coordinator incarnations ago still resolves after the
    second bounce (the RESUMED frame journals its replacement qid and
    replay collapses the chain)."""
    cfg = NodeConfig({"coordinator.journal-path": str(tmp_path / "jr")})
    c1 = CoordinatorServer(config=cfg).start()
    for _ in range(4):
        c1._admit.acquire()
    q = c1.submit("select count(*) as c from tpch.tiny.region")
    port = c1.port
    c1.shutdown()
    # boot 2 resumes the query but we hold ITS admission too, so the
    # resumed run is still open when boot 2 dies
    c2 = CoordinatorServer(port=port, config=cfg)
    for _ in range(4):
        c2._admit.acquire()
    c2.start()
    assert c2.resumed_queries == 1
    assert c2.lookup_query(q.qid) is not None
    c2.shutdown()
    c3 = CoordinatorServer(port=port, config=cfg).start()
    try:
        assert c3.resumed_queries == 1
        # the ORIGINAL boot-1 qid still routes, two bounces later
        q3 = c3.lookup_query(q.qid)
        assert q3 is not None, "boot-1 qid lost after the second bounce"
        assert q3.done.wait(60)
        assert q3.state == "FINISHED", q3.error
        assert q3.rows == [[5]]
    finally:
        c3.shutdown()


def test_recovery_readmission_bypasses_queue_cap(tmp_path):
    """Review finding: replayed queries were admitted by the dead
    incarnation under the same cap — recovery must re-admit ALL of
    them even when their count reaches max_queued_queries, never
    journal a RESUMED that is really a rejection."""
    jdir = tmp_path / "jr"
    j = CoordinatorJournal(str(jdir))
    for i in range(3):
        j.record_submit(f"q_c{i}_dead", "select count(*) as c from tpch.tiny.region")
    c = CoordinatorServer(
        max_queued_queries=2,
        config=NodeConfig({"coordinator.journal-path": str(jdir)}),
    ).start()
    try:
        assert c.resumed_queries == 3
        for i in range(3):
            rq = c.lookup_query(f"q_c{i}_dead")
            assert rq is not None
            assert rq.done.wait(60)
            assert rq.state == "FINISHED", rq.error
    finally:
        c.shutdown()


def test_client_reconnects_across_coordinator_bounce(tmp_path):
    """A paginating client must ride out the bounce: connection resets
    during the restart window retry with jittered backoff (satellite —
    a coordinator restart used to kill every paginating client on the
    first reset), and the resumed query delivers its result without
    resubmission."""
    cfg = NodeConfig({"coordinator.journal-path": str(tmp_path / "jr")})
    c1 = CoordinatorServer(config=cfg).start()
    for _ in range(4):
        c1._admit.acquire()  # keep the query QUEUED across the bounce
    port = c1.port
    client = PrestoTpuClient(
        f"http://127.0.0.1:{port}", timeout_s=90, reconnect_attempts=40
    )
    out, errs = {}, []

    def run():
        try:
            out["res"] = client.execute(
                "select count(*) as c from tpch.tiny.nation"
            )
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=run)
    t.start()
    _wait(
        lambda: any(
            q.state == "QUEUED" for q in c1.queries.values()
        ),
        msg="query queued",
    )
    reconnects = REGISTRY.counter("client.reconnects").total
    c1.shutdown()
    # a real outage window: in-flight long-polls finish, then every
    # poll hits a dead port (connection refused) until the restart —
    # long enough that the client MUST ride it on the reconnect path
    _wait(
        lambda: REGISTRY.counter("client.reconnects").total
        > reconnects,
        timeout=20,
        msg="client entered the reconnect path",
    )
    c2 = CoordinatorServer(port=port, config=cfg).start()
    try:
        t.join(90)
        assert not errs, f"client died across the bounce: {errs}"
        assert [tuple(r) for r in out["res"].rows()] == [(25,)]
        assert REGISTRY.counter("client.reconnects").total > reconnects
        assert c2.resumed_queries >= 1
    finally:
        c2.shutdown()


def test_prepared_registry_survives_bounce(tmp_path):
    cfg = NodeConfig({"coordinator.journal-path": str(tmp_path / "jr")})
    c1 = CoordinatorServer(config=cfg).start()
    q = c1.submit(
        "prepare pj from select count(*) as c from tpch.tiny.region"
    )
    assert q.done.wait(60) and q.state == "FINISHED", q.error
    c1.shutdown()
    c2 = CoordinatorServer(config=cfg).start()
    try:
        # no client-side prepared headers: the registry itself survived
        q2 = c2.submit("execute pj")
        assert q2.done.wait(60)
        assert q2.state == "FINISHED", q2.error
        assert q2.rows == [[5]]
    finally:
        c2.shutdown()


# --------------------------------------------- chaos: pool halving


def test_pool_halving_under_load_zero_failures(tmp_path):
    """Chaos acceptance (a): drain HALF the pool under sustained
    concurrent load, then restore it — zero failed queries, exact
    results throughout."""
    coord, ws = _mk_cluster(tmp_path, n=4, policy="TASK")
    spawned = []
    try:
        expected = _expected_rows(coord, JOIN_SQL)
        faults.configure(
            {
                "seed": 11,
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.05}
                ],
            }
        )
        results, errs = [], []

        def client_loop(ci):
            client = PrestoTpuClient(coord.uri, timeout_s=120)
            for _ in range(2):
                try:
                    results.append(client.execute(JOIN_SQL).rows())
                except Exception as e:
                    errs.append(e)

        threads = [
            threading.Thread(target=client_loop, args=(ci,))
            for ci in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.2)
        # halve the pool mid-load, through the real drain protocol
        for w in ws[:2]:
            rpc.call_json("PUT", w.uri + "/v1/state/drain")
        time.sleep(0.5)
        # ...and restore it with fresh capacity
        cfg = NodeConfig(
            {
                "exchange.spool-path": str(tmp_path / "spool"),
                "exchange.spool-bytes": "64MB",
            }
        )
        spawned = [
            WorkerServer(coordinator_uri=coord.uri, config=cfg).start()
            for _ in range(2)
        ]
        for t in threads:
            t.join(180)
        assert not errs, f"pool halving lost queries: {errs}"
        assert len(results) == 6
        for rows in results:
            assert [tuple(r) for r in rows] == expected
        # the drained half left discovery; the pool recovered to 4
        _wait(
            lambda: len(coord.active_workers()) == 4
            and not any(
                w.node_id in {x.node_id for x in coord.active_workers()}
                for w in ws[:2]
            ),
            timeout=20,
            msg="pool recovery",
        )
    finally:
        _teardown(coord, ws + spawned)


# ------------------------------------------- preemptible scheduling


def test_merge_stage_placed_on_stable_nodes(tmp_path):
    """Preemptible-aware placement: merge tasks (the only copy of their
    partition's FINAL state) go to stable nodes; preemptibles keep the
    spool-backed producer work."""
    coord, ws = _mk_cluster(tmp_path, n=2, policy="TASK", preemptible={1})
    try:
        _wait(
            lambda: any(
                w.preemptible for w in coord.active_workers()
            ),
            msg="preemptible flag announced",
        )
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute(JOIN_SQL)
        info = client.query_info(res.query_id)
        merge = [st for st in info["stages"] if st["kind"] == "merge"]
        assert merge, info["stages"]
        stable_id = ws[0].node_id
        for st in merge:
            for t in st["tasks"]:
                assert t["node_id"] == stable_id, (
                    f"merge task {t['task_id']} landed on a "
                    f"preemptible node {t['node_id']}"
                )
        # producers used the whole pool, preemptible included
        prod_nodes = {
            t["node_id"]
            for st in info["stages"]
            if st["kind"] == "producer"
            for t in st["tasks"]
        }
        assert ws[1].node_id in prod_nodes
    finally:
        _teardown(coord, ws)


def test_preemption_notice_drains_and_reschedules(tmp_path):
    """kill_worker_preempt: the preemption notice lands mid-task on the
    preemptible worker — it drains immediately (new work reschedules on
    the stable node), the query completes exactly, and the preempted
    worker exits clean."""
    coord, ws = _mk_cluster(tmp_path, n=2, policy="TASK", preemptible={1})
    try:
        expected = _expected_rows(coord, JOIN_SQL)
        before = REGISTRY.counter("pool.preemptions").total
        faults.configure(
            {
                "seed": 13,
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.05},
                    {
                        "action": "kill_worker_preempt",
                        "node": ws[1].node_id,
                        "count": 1,
                    },
                ],
            }
        )
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute(JOIN_SQL)
        assert [tuple(r) for r in res.rows()] == expected
        assert (
            REGISTRY.counter("pool.preemptions").total == before + 1
        )
        # the preempted worker drained out of discovery and exited
        _wait(
            lambda: ws[1].node_id
            not in {w.node_id for w in coord.active_workers()},
            msg="preempted worker left discovery",
        )
        _wait(
            lambda: ws[1]._shutting_down,
            timeout=20,
            msg="preempted worker exit",
        )
        # follow-up queries keep completing on the survivor
        res2 = client.execute(
            "select count(*) as c from tpch.tiny.orders"
        )
        assert [tuple(r) for r in res2.rows()] == [(15000,)]
    finally:
        _teardown(coord, ws)


# -------------------------------------------------------- autoscaler


class _FakeProvider(WorkerPoolProvider):
    def __init__(self):
        self.spawned, self.drained = [], []

    def spawn(self):
        nid = f"fake-{len(self.spawned)}"
        self.spawned.append(nid)
        return nid

    def drain(self, node_id):
        self.drained.append(node_id)


def test_autoscaler_hysteresis_no_flapping():
    """Oscillating load must RATCHET capacity up and hold it — never
    up-down-up (scale-down needs consecutive idle ticks + cooldown);
    sustained idle then drains exactly back to the floor, once."""
    prov = _FakeProvider()
    a = Autoscaler(
        None, prov, min_workers=1, max_workers=3,
        interval_s=1.0, scale_down_ticks=3, cooldown_s=2.0,
    )
    now, n = 0.0, 1

    def tick(queued):
        nonlocal now, n
        now += 1.0
        a.step(queued=queued, running=0, backlog=0, n_workers=n, now=now)
        n = 1 + len(prov.spawned) - len(prov.drained)

    for i in range(20):  # oscillating: busy, idle, busy, idle, ...
        tick(i % 2)
    assert len(prov.spawned) == 2  # ratcheted to max_workers
    assert len(prov.drained) == 0, "flapped down under oscillation"
    for _ in range(20):  # sustained idle: drain to the floor
        tick(0)
    assert n == 1
    assert len(prov.drained) == 2
    s0, d0 = len(prov.spawned), len(prov.drained)
    for _ in range(10):  # stability: no further actions
        tick(0)
    assert (len(prov.spawned), len(prov.drained)) == (s0, d0)
    assert a.last_decision == "hold"


def test_autoscaler_keeps_ttl_flapped_workers_owned():
    """Review finding: a live worker whose announcement lapses the
    discovery TTL (slow announce, flaky link) must stay OWNED — only a
    provider-disowned node (really dead) is forgotten; otherwise the
    pool can never drain back below the flapped node's capacity."""

    class _StubCoord:
        def __init__(self):
            self._pool_scaling = set()
            self.pool_decision = ""

        def load_snapshot(self):
            return {"queued": 0, "running": 0, "backlog": 0}

        def _ttl_workers(self):
            return []  # the flap: nothing announced right now

    class _OwningProvider(_FakeProvider):
        def __init__(self):
            super().__init__()
            self.dead = set()

        def owns(self, node_id):
            return node_id not in self.dead

    prov = _OwningProvider()
    a = Autoscaler(
        _StubCoord(), prov, min_workers=0, max_workers=2,
        interval_s=1.0,
    )
    a.owned = ["fake-alive", "fake-dead"]
    prov.dead.add("fake-dead")
    a._tick()
    assert a.owned == ["fake-alive"], a.owned


def test_autoscaler_live_scale_up_and_down():
    """Queue depth scales the live pool up through the provider;
    drained-back capacity routes through the drain protocol; decisions
    surface on coordinator.pool_decision."""
    coord = CoordinatorServer().start()
    prov = LocalWorkerPoolProvider(coord.uri)
    up0 = REGISTRY.counter("pool.scale_up").total
    down0 = REGISTRY.counter("pool.scale_down").total
    coord.attach_pool(
        prov, min_workers=1, max_workers=3, interval_s=0.05,
        scale_down_ticks=2, cooldown_s=0.1,
    )
    try:
        _wait(
            lambda: len(coord.active_workers()) >= 1,
            msg="floor spawn",
        )
        # queue pressure: hold admission so submissions stay QUEUED
        for _ in range(4):
            coord._admit.acquire()
        qs = [
            coord.submit("select count(*) as c from tpch.tiny.region")
            for _ in range(4)
        ]
        _wait(
            lambda: len(coord.active_workers()) >= 3,
            timeout=30,
            msg="scale-up on queue depth",
        )
        assert REGISTRY.counter("pool.scale_up").total >= up0 + 3
        for _ in range(4):
            coord._admit.release()
        for q in qs:
            assert q.done.wait(60)
            assert q.state == "FINISHED", q.error
        _wait(
            lambda: len(coord.active_workers()) <= 1,
            timeout=30,
            msg="scale-down to the floor",
        )
        assert REGISTRY.counter("pool.scale_down").total >= down0 + 2
        # stability after the drain-down: the decision settles on hold
        time.sleep(0.5)
        assert coord.pool_decision == "hold"
        assert len(coord.active_workers()) == 1
    finally:
        coord.shutdown()
        for w in list(prov.workers.values()):
            w.shutdown(graceful=False)


def test_nodes_view_preemptible_and_pool_state(tmp_path):
    coord, ws = _mk_cluster(tmp_path, n=2, policy="NONE", preemptible={1})
    try:
        _wait(
            lambda: any(
                w.preemptible for w in coord.active_workers()
            ),
            msg="preemptible flag announced",
        )
        coord.pool_decision = "scale_up(queued=2): worker-test"
        rows = coord.local.execute(
            "select node_id, coordinator, preemptible, pool_state, "
            "last_decision from system.runtime.nodes"
        ).rows()
        by_id = {r[0]: r for r in rows}
        assert by_id[ws[0].node_id][2] is False
        assert by_id[ws[1].node_id][2] is True
        assert by_id[ws[1].node_id][3] == "STABLE"
        assert (
            by_id["coordinator"][4]
            == "scale_up(queued=2): worker-test"
        )
        assert by_id[ws[0].node_id][4] == ""  # decision: coord row only
        # a draining node reports DRAINING pool state
        ws[1]._draining = True
        ws[1]._announce_once()
        _wait(
            lambda: any(
                w.state == "DRAINING"
                for w in coord.nodes()
                if w.node_id == ws[1].node_id
            ),
            msg="drain announced",
        )
        rows = coord.local.execute(
            "select node_id, pool_state from system.runtime.nodes"
        ).rows()
        assert dict(rows)[ws[1].node_id] == "DRAINING"
        # SCALING_UP: spawned by the autoscaler, not yet announced
        coord._pool_scaling.add("worker-booting")
        coord.announce("worker-booting", "http://127.0.0.1:9", "ACTIVE")
        fake = next(
            w for w in coord.nodes() if w.node_id == "worker-booting"
        )
        assert coord.pool_state(fake) == "SCALING_UP"
    finally:
        _teardown(coord, ws)


# ---------------------------------------------------- config + lint


def test_launcher_parses_pool_and_journal_config(tmp_path):
    from presto_tpu.server.launcher import load_etc

    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(
        "coordinator=true\n"
        f"coordinator.journal-path={tmp_path}/journal\n"
        "pool.min-workers=1\n"
        "pool.max-workers=8\n"
        "pool.scale-interval-s=0.5\n"
        "pool.scale-down-ticks=4\n"
        "pool.preempt-grace-s=5\n"
        "node.preemptible=true\n"
    )
    (etc / "catalog" / "tpch.properties").write_text(
        "connector.name=tpch\n"
    )
    config, _catalogs = load_etc(str(etc))
    assert config.get("pool.min-workers") == 1
    assert config.get("pool.max-workers") == 8
    assert config.get("pool.scale-interval-s") == 0.5
    assert config.get("coordinator.journal-path") == f"{tmp_path}/journal"
    assert config.get("node.preemptible") is True


def test_kill_worker_preempt_rule_validates():
    plane = faults.configure(
        {"rules": [{"action": "kill_worker_preempt", "node": "w1"}]}
    )
    fired = []
    plane.on_task("w1-abc", "q.t.0.a0", preempt=lambda: fired.append(1))
    assert fired == [1]
    faults.configure(None)
    with pytest.raises(ValueError):
        faults.FaultRule.from_dict({"action": "preempt_everything"})


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
