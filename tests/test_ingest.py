"""Streaming ingest lane + incrementally-maintained materialized
views (server/ingest.py, exec/mview.py, the memory connector's
snapshot SPI).

Covers the PR's acceptance contracts: WAL round-trip with torn-tail
replay, snapshot isolation under concurrent append (a reader pinned
mid-scan sees ONE version), kill-mid-commit chaos (replay loses zero
committed batches and duplicates zero), incremental-vs-full-refresh
bit-equality for every eligible aggregate, ineligible-view fallback,
the staleness read gate, the HTTP ingest endpoint, runtime views +
metrics, and the legacy write path staying bit-exact when
``ingest.wal-path`` is unset.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.server.ingest import (
    IngestError,
    IngestManager,
    _parse_wal_line,
    _wal_frame,
)
from presto_tpu.utils.metrics import REGISTRY


def fresh_runner():
    """A runner with a FRESH memory connector (the crash-simulation
    primitive: a new connector is an empty volatile store)."""
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    catalogs.register("mem", mem)
    return LocalQueryRunner(catalogs=catalogs), mem


def make_events(mem, name="ev"):
    mem.create_table(
        TableHandle("mem", "default", name),
        {"k": T.BIGINT, "v": T.BIGINT, "w": T.DOUBLE},
    )
    return TableHandle("mem", "default", name)


@pytest.fixture()
def lane(tmp_path):
    runner, mem = fresh_runner()
    make_events(mem)
    ing = IngestManager(runner, str(tmp_path), start_thread=False)
    yield runner, mem, ing, str(tmp_path)
    ing.close(final_flush=False)


# ------------------------------------------------------------- the WAL


def test_wal_round_trip_and_commit_visibility(lane):
    runner, mem, ing, _ = lane
    out = ing.append(
        "mem.default.ev",
        columns={"k": [1, 1, 2], "v": [10, 20, 5], "w": [1.0, 2.0, 0.5]},
    )
    assert out["seq"] == 1 and out["rows"] == 3
    # durable but NOT visible before the commit folds it
    assert runner.execute(
        "select count(*) from mem.default.ev"
    ).rows() == [(0,)]
    assert ing.commit_tick() == 1
    assert runner.execute(
        "select k, v from mem.default.ev order by v"
    ).rows() == [(2, 5), (1, 10), (1, 20)]
    # the table now has a committed snapshot the planner pins
    assert mem.current_snapshot_id(
        TableHandle("mem", "default", "ev")
    ) == 1


def test_append_validates_schema(lane):
    _runner, _mem, ing, _ = lane
    with pytest.raises(IngestError, match="unknown column"):
        ing.append("mem.default.ev", columns={"nope": [1]})
    # the rows form must be just as strict: a typo'd key must error,
    # never silently NULL-fill the real column under a 200 ack
    with pytest.raises(IngestError, match="unknown column"):
        ing.append(
            "mem.default.ev",
            rows=[{"K": 1, "v": 2, "w": 0.1}],
        )
    with pytest.raises(IngestError, match="missing column"):
        ing.append("mem.default.ev", rows=[{"k": 1, "v": 2}])
    with pytest.raises(IngestError, match="missing column"):
        ing.append("mem.default.ev", columns={"k": [1]})
    with pytest.raises(IngestError, match="ragged"):
        ing.append(
            "mem.default.ev",
            columns={"k": [1], "v": [1, 2], "w": [0.1]},
        )
    with pytest.raises(IngestError, match="zero rows"):
        ing.append(
            "mem.default.ev", columns={"k": [], "v": [], "w": []}
        )


def test_wal_frame_round_trip_and_corruption():
    rec = {"ev": "batch", "seq": 3, "cols": {"k": [1]}}
    line = _wal_frame(json.dumps(rec))
    assert _parse_wal_line(line) == rec
    # torn tail: any truncation breaks the crc
    for cut in (len(line) - 1, len(line) // 2, 9):
        assert _parse_wal_line(line[:cut]) is None
    assert _parse_wal_line("zzzzzzzz {}") is None
    assert _parse_wal_line("") is None


def test_torn_tail_replay_readmits_exactly_once(lane, tmp_path):
    runner, mem, ing, wal = lane
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [10], "w": [1.0]},
    )
    ing.commit_tick()
    ing.append(
        "mem.default.ev",
        columns={"k": [2], "v": [20], "w": [2.0]},
    )
    # crash before the second commit, tearing the tail frame mid-write
    path = os.path.join(wal, "wal-mem.default.ev.jsonl")
    with open(path, "a", encoding="utf-8") as f:
        f.write("deadbeef {\"ev\": \"batch\", \"seq\"")  # torn line
    corrupt0 = int(REGISTRY.counter("ingest.wal_corrupt").total)
    runner2, _mem2 = fresh_runner()
    ing2 = IngestManager(runner2, wal, start_thread=False)
    # committed batch 1 is back; uncommitted batch 2 is PENDING (not
    # yet visible), re-admitted exactly once
    assert runner2.execute(
        "select k, v from mem.default.ev order by k"
    ).rows() == [(1, 10)]
    assert ing2.stats()["pending_batches"] == 1
    assert (
        int(REGISTRY.counter("ingest.wal_corrupt").total) == corrupt0 + 1
    )
    ing2.commit_tick()
    assert runner2.execute(
        "select k, v from mem.default.ev order by k"
    ).rows() == [(1, 10), (2, 20)]
    # a THIRD boot replays both batches as committed — no duplicates
    runner3, _mem3 = fresh_runner()
    IngestManager(runner3, wal, start_thread=False)
    assert runner3.execute(
        "select k, v from mem.default.ev order by k"
    ).rows() == [(1, 10), (2, 20)]


def test_legacy_write_path_untouched_without_wal():
    """ingest.wal-path unset: no IngestManager constructs, no
    snapshots mint, plain INSERT/CTAS behave bit-exactly pre-PR."""
    runner, mem = fresh_runner()
    make_events(mem)
    assert runner.ingest is None
    runner.execute(
        "insert into mem.default.ev values (1, 10, 1.0), (2, 20, 2.0)"
    )
    handle = TableHandle("mem", "default", "ev")
    assert mem.current_snapshot_id(handle) is None
    # unversioned tables never pin: the planner's handle is unchanged
    assert mem.pin_snapshot(handle) is handle
    assert runner.execute(
        "select k, v from mem.default.ev order by k"
    ).rows() == [(1, 10), (2, 20)]
    assert runner.execute("delete from mem.default.ev where k = 1").rows() == [
        (1,)
    ]
    assert runner.execute(
        "select k from mem.default.ev"
    ).rows() == [(2,)]


# -------------------------------------------------- snapshot isolation


def test_pinned_snapshot_reader_sees_one_version(lane):
    """A handle pinned at plan time keeps serving its version while
    commits land: splits, stats, and page sources all clamp to the
    pinned prefix."""
    runner, mem, ing, _ = lane
    handle = TableHandle("mem", "default", "ev")
    ing.append(
        "mem.default.ev",
        columns={"k": [1, 2], "v": [10, 20], "w": [1.0, 2.0]},
    )
    ing.commit_tick()
    pinned = mem.pin_snapshot(handle)
    assert pinned.snapshot == 1
    # a commit lands AFTER the reader pinned
    ing.append(
        "mem.default.ev",
        columns={"k": [3], "v": [30], "w": [3.0]},
    )
    ing.commit_tick()
    # the pinned reader still sees exactly version 1 ...
    src = mem.get_splits(pinned)
    rows = 0
    while not src.exhausted:
        for sp in src.next_batch(16):
            rows += len(
                mem.create_page_source(sp, ["k"])["k"]
            )
    assert rows == 2
    assert mem.metadata().get_table_stats(pinned).row_count == 2.0
    # ... and a split minted before the commit cannot widen past it
    from presto_tpu.connectors.spi import ConnectorSplit

    wide = mem.create_page_source(
        ConnectorSplit(pinned, 0, 99), ["k", "v"]
    )
    assert len(wide["k"]) == 2
    # a fresh pin sees version 2
    assert mem.pin_snapshot(handle).snapshot == 2
    assert runner.execute(
        "select count(*) from mem.default.ev"
    ).rows() == [(3,)]


@pytest.mark.slow
def test_snapshot_isolation_under_concurrent_append(lane):
    """Writers hammer the lane while readers scan: every result is a
    consistent prefix — COUNT and SUM always agree with some committed
    snapshot, never a torn batch."""
    runner, _mem, ing, _ = lane
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                ing.append(
                    "mem.default.ev",
                    columns={
                        "k": [i, i],
                        "v": [1, 1],
                        "w": [0.5, 0.5],
                    },
                )
                ing.commit_tick()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    def reader():
        while not stop.is_set():
            try:
                (n, s), = runner.execute(
                    "select count(*) c, sum(v) s "
                    "from mem.default.ev"
                ).rows()
                # every batch is (2 rows, sum 2): any consistent
                # prefix has s == n and n even
                if n and (s != n or n % 2):
                    errors.append(
                        AssertionError(f"torn read: n={n} s={s}")
                    )
                    return
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader) for _ in range(2)
    ]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(30)
    assert not errors, errors[0]


# ------------------------------------------------- kill-mid-commit chaos


@pytest.mark.slow
def test_kill_mid_commit_chaos_replay_exact_once(tmp_path):
    """Crash at every point of the commit pipeline (frame written /
    not written, connector folded / not): replay must expose every
    batch whose commit frame landed EXACTLY once, keep the rest
    pending exactly once, and an MV over the replayed table must equal
    a cold full refresh bit-for-bit."""
    wal = str(tmp_path)
    runner, mem = fresh_runner()
    make_events(mem)
    ing = IngestManager(runner, wal, start_thread=False)
    runner.execute(
        "create materialized view mem.default.mv as "
        "select k, sum(v) as sv, count(*) as c "
        "from mem.default.ev group by k"
    )
    committed_rows = []  # (k, v) rows covered by a commit frame
    tail_rows = []  # appended, no commit frame yet
    seq = 0
    for round_no in range(6):
        seq += 1
        rows = [(round_no % 3, 10 + seq), (round_no % 3 + 5, seq)]
        ing.append(
            "mem.default.ev",
            columns={
                "k": [r[0] for r in rows],
                "v": [r[1] for r in rows],
                "w": [0.0, 0.0],
            },
        )
        tail_rows.extend(rows)
        kill_point = round_no % 3
        if kill_point == 0:
            # crash BEFORE the commit frame: batch stays tail
            pass
        elif kill_point == 1:
            # full commit, then crash
            ing.commit_tick()
            committed_rows.extend(tail_rows)
            tail_rows = []
        else:
            # crash BETWEEN the commit frame and the connector fold:
            # simulate by writing the commit frame by hand through the
            # manager's own framing (the connector never sees it)
            with ing._commit_mu:
                lane_obj = ing._lane(
                    TableHandle("mem", "default", "ev")
                )
                with lane_obj.lock:
                    batches = lane_obj.pending
                    lane_obj.pending = []
                    upto = batches[-1][0]
                    ing._write_frame(
                        lane_obj,
                        {
                            "ev": "commit",
                            "upto": upto,
                            "snapshot": upto,
                        },
                    )
                    lane_obj.committed = upto
            committed_rows.extend(tail_rows)
            tail_rows = []
        # "kill": fresh store + fresh manager over the same WAL
        runner, mem = fresh_runner()
        ing = IngestManager(runner, wal, start_thread=False)
        got = runner.execute(
            "select k, v from mem.default.ev order by k, v"
        ).rows()
        assert got == sorted(committed_rows), (
            f"round {round_no}: committed batches lost or duplicated"
        )
        assert ing.stats()["pending_batches"] == len(tail_rows) // 2
        # MV over the replayed table == cold full refresh, bit-for-bit
        mv_rows = runner.execute(
            "select * from mem.default.mv order by k"
        ).rows()
        cold = runner.execute(
            "select k, sum(v) as sv, count(*) as c "
            "from mem.default.ev group by k order by k"
        ).rows()
        assert mv_rows == cold, f"round {round_no}: MV != cold refresh"
    # final commit folds the surviving tail exactly once
    ing.commit_tick()
    committed_rows.extend(tail_rows)
    assert runner.execute(
        "select k, v from mem.default.ev order by k, v"
    ).rows() == sorted(committed_rows)


# --------------------------------------------------- materialized views


def _mv_setup(lane, mv_sql=None):
    runner, mem, ing, wal = lane
    runner.execute(
        mv_sql
        or (
            "create materialized view mem.default.mv as "
            "select k, sum(v) as sv, count(*) as c, min(v) as mn, "
            "max(v) as mx, avg(w) as aw "
            "from mem.default.ev group by k"
        )
    )
    return runner, mem, ing


def test_incremental_vs_full_bit_equality_each_aggregate(lane):
    """Every eligible aggregate (SUM/COUNT/MIN/MAX/AVG) maintained
    incrementally across commits equals a full refresh bit-for-bit —
    and equals the engine running the defining query directly."""
    runner, _mem, ing = _mv_setup(lane)
    batches = [
        {"k": [1, 1, 2], "v": [10, 20, 5], "w": [1.0, 3.0, 0.5]},
        {"k": [2, 3], "v": [7, 100], "w": [2.5, 4.0]},
        {"k": [1, 3, 3], "v": [1, 2, 3], "w": [0.0, 8.0, 4.0]},
    ]
    for b in batches:
        ing.append("mem.default.ev", columns=b)
        ing.commit_tick()
    mv = runner.mview_registry.lookup(("mem", "default", "mv"))
    assert mv.eligible and mv.incremental_refreshes == 3
    incremental = runner.execute(
        "select * from mem.default.mv order by k"
    ).rows()
    direct = runner.execute(
        "select k, sum(v), count(*), min(v), max(v), avg(w) "
        "from mem.default.ev group by k order by k"
    ).rows()
    assert incremental == direct
    # full refresh over the same base: bit-identical stored contents
    runner.execute("refresh materialized view mem.default.mv")
    assert mv.last_mode == "full"
    full = runner.execute(
        "select * from mem.default.mv order by k"
    ).rows()
    assert full == incremental


def test_new_groups_appear_incrementally(lane):
    runner, _mem, ing = _mv_setup(lane)
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [10], "w": [1.0]},
    )
    ing.commit_tick()
    ing.append(
        "mem.default.ev",
        columns={"k": [9], "v": [90], "w": [9.0]},
    )
    ing.commit_tick()
    assert runner.execute(
        "select k, sv from mem.default.mv order by k"
    ).rows() == [(1, 10), (9, 90)]


def test_where_clause_applies_to_delta(lane):
    runner, _mem, ing = _mv_setup(
        lane,
        "create materialized view mem.default.mv as "
        "select k, sum(v) as sv from mem.default.ev "
        "where v >= 10 group by k",
    )
    ing.append(
        "mem.default.ev",
        columns={"k": [1, 1], "v": [5, 50], "w": [0.0, 0.0]},
    )
    ing.commit_tick()
    assert runner.execute(
        "select * from mem.default.mv"
    ).rows() == [(1, 50)]
    mv = runner.mview_registry.lookup(("mem", "default", "mv"))
    assert mv.eligible and mv.incremental_refreshes == 1


def test_ineligible_view_falls_back_to_full_refresh(lane):
    """A join view still materializes, but every maintenance event is
    a full recompute (and says so in the runtime view)."""
    runner, _mem, ing = _mv_setup(
        lane,
        "create materialized view mem.default.mvj as "
        "select r_name, count(*) as c "
        "from mem.default.ev, tpch.tiny.region "
        "where k = r_regionkey group by r_name",
    )
    mv = runner.mview_registry.lookup(("mem", "default", "mvj"))
    assert not mv.eligible and mv.reason
    ing.append(
        "mem.default.ev",
        columns={"k": [0, 0, 1], "v": [1, 2, 3], "w": [0.0] * 3},
    )
    ing.commit_tick()
    assert mv.incremental_refreshes == 0 and mv.refreshes == 2
    assert runner.execute(
        "select r_name, c from mem.default.mvj order by r_name"
    ).rows() == runner.execute(
        "select r_name, count(*) from mem.default.ev, tpch.tiny.region "
        "where k = r_regionkey group by r_name order by r_name"
    ).rows()


def test_full_refresh_covers_racing_delta_exactly_once(lane):
    """The double-apply guard: a full refresh that read the base
    at/after commit sid already contains that delta — a late merger
    for the same sid must skip, not double-count."""
    runner, _mem, ing = _mv_setup(
        lane,
        "create materialized view mem.default.mv as "
        "select k, sum(v) as sv from mem.default.ev group by k",
    )
    reg = runner.mview_registry
    mv = reg.lookup(("mem", "default", "mv"))
    delta = {"k": [1], "v": [10], "w": [1.0]}
    ing.append("mem.default.ev", columns=delta)
    ing.commit_tick()  # merges normally; last_snapshot == 1
    assert mv.last_snapshot == 1
    # a straggling merge for an ALREADY-COVERED sid must be a no-op
    reg._incremental_refresh(mv, delta, 1)
    assert runner.execute(
        "select sv from mem.default.mv where k = 1"
    ).rows() == [(10,)]
    # and a REFRESH samples the covered snapshot so the guard holds
    runner.execute("refresh materialized view mem.default.mv")
    assert mv.last_snapshot == 1
    reg._incremental_refresh(mv, delta, 1)
    assert runner.execute(
        "select sv from mem.default.mv where k = 1"
    ).rows() == [(10,)]


def test_incremental_disabled_forces_full(lane):
    runner, _mem, ing = _mv_setup(lane)
    runner.mview_registry.incremental_enabled = False
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [10], "w": [1.0]},
    )
    ing.commit_tick()
    mv = runner.mview_registry.lookup(("mem", "default", "mv"))
    assert mv.incremental_refreshes == 0 and mv.last_mode == "full"
    assert runner.execute(
        "select k, sv from mem.default.mv"
    ).rows() == [(1, 10)]


def test_staleness_read_gate_refreshes_legacy_writes(lane):
    """A base written through the LEGACY path (no commit hook) leaves
    the view stale; the read gate refreshes it in-line once the
    staleness bound expires."""
    runner, _mem, _ing = lane[0], lane[1], lane[2]
    runner.execute(
        "create materialized view mem.default.mv as "
        "select k, sum(v) as sv from mem.default.ev group by k"
    )
    reg = runner.mview_registry
    runner.execute("insert into mem.default.ev values (1, 10, 1.0)")
    # gate off: the view stays stale
    assert runner.execute("select * from mem.default.mv").rows() == []
    # gate on with a tiny bound: the next read refreshes first
    reg.max_staleness_s = 0.01
    time.sleep(0.05)
    assert runner.execute(
        "select * from mem.default.mv"
    ).rows() == [(1, 10)]
    mv = reg.lookup(("mem", "default", "mv"))
    assert mv.refreshes == 2  # create + the gate's refresh
    # fresh view within the bound: no extra refresh on re-read
    reg.max_staleness_s = 3600.0
    runner.execute("insert into mem.default.ev values (2, 20, 2.0)")
    assert runner.execute(
        "select * from mem.default.mv order by k"
    ).rows() == [(1, 10)]
    assert mv.refreshes == 2


def test_gate_repairs_view_after_legacy_write_between_commits(lane):
    """A legacy INSERT between ingest commits rides into the next
    snapshot but NOT into the incremental delta: the merge must not
    mark the view fresh for it (epoch attribution), so the staleness
    gate still repairs the divergence."""
    runner, _mem, ing = _mv_setup(
        lane,
        "create materialized view mem.default.mv as "
        "select k, sum(v) as sv from mem.default.ev group by k",
    )
    reg = runner.mview_registry
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [10], "w": [0.0]},
    )
    ing.commit_tick()
    # LEGACY write between commits: no commit hook, no delta
    runner.execute("insert into mem.default.ev values (2, 20, 0.0)")
    ing.append(
        "mem.default.ev",
        columns={"k": [3], "v": [30], "w": [0.0]},
    )
    ing.commit_tick()
    # the merge carried only the ingest delta — k=2 is missing, and
    # the view must still be CONSIDERED stale (not masked as fresh)
    assert runner.execute(
        "select k, sv from mem.default.mv order by k"
    ).rows() == [(1, 10), (3, 30)]
    reg.max_staleness_s = 0.01
    time.sleep(0.05)
    assert runner.execute(
        "select k, sv from mem.default.mv order by k"
    ).rows() == [(1, 10), (2, 20), (3, 30)]


def test_drop_materialized_view(lane):
    runner, _mem, _ing = _mv_setup(lane)
    runner.execute("drop materialized view mem.default.mv")
    assert runner.mview_registry.lookup(
        ("mem", "default", "mv")
    ) is None
    with pytest.raises(Exception):
        runner.execute("select * from mem.default.mv")
    # IF EXISTS is quiet
    runner.execute(
        "drop materialized view if exists mem.default.mv"
    )


def test_mview_definition_survives_replay(lane):
    runner, _mem, ing = _mv_setup(lane)
    ing.append(
        "mem.default.ev",
        columns={"k": [1, 2], "v": [10, 20], "w": [1.0, 2.0]},
    )
    ing.commit_tick()
    wal = lane[3]
    runner2, _mem2 = fresh_runner()
    IngestManager(runner2, wal, start_thread=False)
    mv = runner2.mview_registry.lookup(("mem", "default", "mv"))
    assert mv is not None and mv.last_mode == "replay"
    assert runner2.execute(
        "select k, sv from mem.default.mv order by k"
    ).rows() == [(1, 10), (2, 20)]


def test_replay_without_catalog_preserves_seq_watermarks(tmp_path):
    """A WAL whose catalog is not mounted at replay cannot restore its
    data, but the lane's seq/committed watermarks MUST restore — a
    later append reusing a committed seq would make the next replay
    promote the wrong batch to committed."""
    wal = str(tmp_path)
    runner, mem = fresh_runner()
    make_events(mem)
    ing = IngestManager(runner, wal, start_thread=False)
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [111], "w": [0.0]},
    )
    ing.commit_tick()
    # boot 2: mem catalog NOT mounted — data unrestorable, watermarks
    # preserved
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    r2 = LocalQueryRunner(catalogs=catalogs)
    ing2 = IngestManager(r2, wal, start_thread=False)
    lane_obj = ing2._lane(TableHandle("mem", "default", "ev"))
    assert lane_obj.seq == 1 and lane_obj.committed == 1
    # late-mounted catalog: new appends mint FRESH seqs past the
    # committed watermark
    mem2 = create_connector("memory")
    make_events(mem2)
    r2.catalogs.register("mem", mem2)
    out = ing2.append(
        "mem.default.ev",
        columns={"k": [2], "v": [222], "w": [0.0]},
    )
    assert out["seq"] == 2
    ing2.commit_tick()
    # boot 3 with the catalog mounted: both batches exactly once
    runner3, _mem3 = fresh_runner()
    IngestManager(runner3, wal, start_thread=False)
    assert runner3.execute(
        "select k, v from mem.default.ev order by k"
    ).rows() == [(1, 111), (2, 222)]


def test_replay_applies_committed_into_recreated_empty_table(tmp_path):
    """The idempotent-setup pattern: an embedder re-runs CREATE TABLE
    on the fresh store before recovery. An existing-but-EMPTY table
    must still get its committed WAL rows back (only a table WITH
    data is assumed live)."""
    wal = str(tmp_path)
    runner, mem = fresh_runner()
    make_events(mem)
    ing = IngestManager(runner, wal, start_thread=False)
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [111], "w": [0.0]},
    )
    ing.commit_tick()
    runner2, mem2 = fresh_runner()
    make_events(mem2)  # re-created EMPTY before the manager constructs
    IngestManager(runner2, wal, start_thread=False)
    assert runner2.execute(
        "select k, v from mem.default.ev"
    ).rows() == [(1, 111)]


def test_failed_merge_poisons_incremental_until_full_refresh(lane):
    """A merge that dies loses its drained deltas: the view must NOT
    keep merging on top of the hole — the next maintenance event falls
    back to a full refresh and heals it (dirty flag)."""
    runner, _mem, ing = _mv_setup(
        lane,
        "create materialized view mem.default.mv as "
        "select k, sum(v) as sv from mem.default.ev group by k",
    )
    reg = runner.mview_registry
    mv = reg.lookup(("mem", "default", "mv"))
    orig = reg._merge_one_delta
    def boom(*a, **k):
        raise RuntimeError("injected merge failure")
    reg._merge_one_delta = boom
    try:
        ing.append(
            "mem.default.ev",
            columns={"k": [1], "v": [10], "w": [0.0]},
        )
        ing.commit_tick()  # maintenance error absorbed by the lane
    finally:
        reg._merge_one_delta = orig
    assert mv.dirty  # the hole is recorded
    assert int(
        REGISTRY.counter("mview.maintenance_errors").total
    ) >= 1
    # next commit repairs via FULL refresh, then incremental resumes
    ing.append(
        "mem.default.ev",
        columns={"k": [2], "v": [20], "w": [0.0]},
    )
    ing.commit_tick()
    assert not mv.dirty and mv.last_mode == "full"
    assert runner.execute(
        "select k, sv from mem.default.mv order by k"
    ).rows() == [(1, 10), (2, 20)]
    ing.append(
        "mem.default.ev",
        columns={"k": [1], "v": [5], "w": [0.0]},
    )
    ing.commit_tick()
    assert mv.last_mode == "incremental"
    assert runner.execute(
        "select k, sv from mem.default.mv order by k"
    ).rows() == [(1, 15), (2, 20)]


# -------------------------------------------- server + runtime surface


def test_coordinator_endpoint_and_runtime_views(tmp_path):
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.session import NodeConfig

    cfg = NodeConfig(
        {
            "ingest.wal-path": str(tmp_path),
            "ingest.commit-interval-ms": "0",  # explicit commits only
            "mview.max-staleness-s": "30",
            "mview.incremental-enabled": "true",
        }
    )
    coord = CoordinatorServer(config=cfg).start()
    try:
        coord.local.catalogs.register("mem", create_connector("memory"))
        coord.local.execute(
            "create table mem.default.ev (k bigint, v bigint)"
        )
        coord.local.execute(
            "create materialized view mem.default.mv as "
            "select k, sum(v) as sv from mem.default.ev group by k"
        )
        req = urllib.request.Request(
            coord.uri + "/v1/ingest/mem.default.ev",
            data=json.dumps(
                {
                    "rows": [{"k": 1, "v": 10}, {"k": 2, "v": 7}],
                    "commit": True,
                }
            ).encode(),
        )
        out = json.load(urllib.request.urlopen(req))
        assert out["rows"] == 2 and out["committed"]
        assert coord.local.execute(
            "select * from mem.default.mv order by k"
        ).rows() == [(1, 10), (2, 7)]
        # columnar form + rejection of a bad column
        req = urllib.request.Request(
            coord.uri + "/v1/ingest/mem.default.ev",
            data=json.dumps(
                {"columns": {"k": [3], "v": [1]}, "commit": True}
            ).encode(),
        )
        assert json.load(urllib.request.urlopen(req))["rows"] == 1
        bad = urllib.request.Request(
            coord.uri + "/v1/ingest/mem.default.ev",
            data=json.dumps({"columns": {"bogus": [1]}}).encode(),
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad)
        assert ei.value.code == 400
        # runtime views
        rtv = coord.local.execute(
            "select view, base_table, eligible, last_refresh_mode, "
            "incremental_refreshes "
            "from system.runtime.materialized_views"
        ).rows()
        assert rtv == [
            ("mem.default.mv", "mem.default.ev", True, "incremental", 2)
        ]
        caches = dict(
            (r[0], r)
            for r in coord.local.execute(
                "select cache, entries, hits "
                "from system.runtime.caches"
            ).rows()
        )
        assert "ingest.wal" in caches
        assert caches["ingest.wal"][2] >= 2  # commits as hits
        # metrics flowed
        names = {
            n
            for n, _k, _v in REGISTRY.snapshot()
        }
        for expect in (
            "ingest.batches.total",
            "ingest.rows.total",
            "ingest.wal_bytes.total",
            "ingest.commit_ms.count",
            "mview.refreshes.total",
            "mview.incremental_refreshes.total",
            "mview.rows_delta.total",
            "mview.staleness_ms.count",
        ):
            assert expect in names, expect
    finally:
        coord.shutdown()


def test_endpoint_without_lane_is_503(tmp_path):
    from presto_tpu.server.coordinator import CoordinatorServer

    coord = CoordinatorServer().start()
    try:
        req = urllib.request.Request(
            coord.uri + "/v1/ingest/mem.default.ev",
            data=b"{}",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 503
    finally:
        coord.shutdown()


@pytest.mark.slow
def test_commit_loop_drives_visibility(tmp_path):
    """The background commit loop (no explicit flush) folds pending
    batches and maintains the view."""
    runner, mem = fresh_runner()
    make_events(mem)
    ing = IngestManager(
        runner, str(tmp_path), commit_interval_ms=20.0
    )
    try:
        runner.execute(
            "create materialized view mem.default.mv as "
            "select k, sum(v) as sv from mem.default.ev group by k"
        )
        ing.append(
            "mem.default.ev",
            columns={"k": [1], "v": [10], "w": [1.0]},
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if runner.execute(
                "select * from mem.default.mv"
            ).rows() == [(1, 10)]:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                "commit loop never surfaced the batch in the view"
            )
    finally:
        ing.close()
