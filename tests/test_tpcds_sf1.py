"""TPC-DS official corpus at SF1, oracle-exact (VERDICT r4 ask 7).

The tiny-scale suite (tests/test_tpcds.py) proves semantics; this tier
proves the closed-form generators' cardinality/skew holds up at SF1
(2.88M store_sales, 23.5M inventory) and that the engine's fragment
executor + spill paths survive real fact-table sizes on the CPU
backend. Marked ``slow`` — excluded from the default run (pytest.ini),
executed explicitly with ``python -m pytest -m slow tests/ -q``.

The sqlite oracle builds *_sk indexes at load (verifier.load_table) so
its own join plans stay suite-tolerable at this scale.
"""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query

from presto_tpu.queries_tpcds import OFFICIAL, official_for, queries_for

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("sf1", catalog="tpcds")


_SF1 = official_for("sf1")


@pytest.mark.parametrize("name", sorted(OFFICIAL))
def test_tpcds_official_sf1(name, runner, oracle):
    diff = verify_query(runner, oracle, _SF1[name], rel_tol=1e-6)
    assert diff is None, f"{name}@sf1 mismatch: {diff}"


def test_tpcds_q95_sf1(runner, oracle):
    _, q95, _ = queries_for("sf1")
    diff = verify_query(runner, oracle, q95, rel_tol=1e-6)
    assert diff is None, f"q95@sf1 mismatch: {diff}"


def test_tpcds_q64_sf1(runner, oracle):
    q64, _, _ = queries_for("sf1")
    diff = verify_query(runner, oracle, q64, rel_tol=1e-6)
    assert diff is None, f"q64@sf1 mismatch: {diff}"
