"""Cluster memory governance (reference: ClusterMemoryManager +
low-memory killer + spilling, SURVEY.md §2.1 "Memory manager"):
distributed accounting on the heartbeats, the cluster arbiter's
quotas/admission/killer, the host-spill degradation lane, and the
memory fault rules."""

import os
import threading
import time

import numpy as np
import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.server.coordinator import CoordinatorServer
from presto_tpu.server.memory_arbiter import ClusterMemoryArbiter
from presto_tpu.server.worker import WorkerServer
from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.memory import MemoryLimitExceeded, MemoryPool
from presto_tpu.utils.metrics import REGISTRY


# ------------------------------------------------------------ pool lanes


def test_pool_tracks_peak_and_blocked():
    p = MemoryPool(1000)
    p.reserve("q1", 600)
    p.release("q1", 200)
    assert p.used_bytes("q1") == 400
    assert p.peak_bytes("q1") == 600
    snap = p.snapshot()
    assert snap["used"]["q1"] == 400 and snap["peak"]["q1"] == 600
    p.release("q1")
    assert p.peak_bytes("q1") == 0  # peak dies with the reservation


def test_blocking_reserve_waits_for_headroom():
    p = MemoryPool(1000)
    p.block_timeout_s = 5.0
    p.reserve("q1", 900)
    got = []
    t = threading.Thread(
        target=lambda: (p.reserve("q2", 500), got.append("ok"))
    )
    t.start()
    time.sleep(0.15)
    blocked = p.blocked()
    assert len(blocked) == 1
    assert blocked[0]["owner"] == "q2"
    assert blocked[0]["bytes"] == 500
    assert blocked[0]["age_s"] > 0.05
    p.release("q1")  # headroom appears -> the wait resolves
    t.join(3)
    assert got == ["ok"]
    assert p.used_bytes() == 500


def test_blocking_reserve_times_out():
    p = MemoryPool(100)
    p.block_timeout_s = 0.2
    p.reserve("q1", 90)
    with pytest.raises(MemoryLimitExceeded, match="blocked past"):
        p.reserve("q2", 50)
    assert p.blocked() == []  # the waiter unregistered


def test_cancel_blocked_fails_waiter_without_poisoning():
    p = MemoryPool(100)
    p.block_timeout_s = 5.0
    p.reserve("q1", 90)
    errs = []

    def waiter():
        try:
            p.reserve("q2", 50)
        except MemoryLimitExceeded as e:
            errs.append(str(e))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    assert p.cancel_blocked("q2") == 1
    t.join(3)
    assert errs and "cancelled" in errs[0]
    # unlike mark_dead, the owner may reserve again (re-admission)
    p.release("q1")
    p.reserve("q2", 50)
    assert p.used_bytes("q2") == 50


def test_fault_reserve_fail_at_nth_reserve():
    p = MemoryPool(1 << 20)
    faults.configure(
        {"rules": [{"action": "reserve_fail", "owner": "qf",
                    "skip": 1, "count": 1}]}
    )
    try:
        p.reserve("qf", 10)  # skipped
        with pytest.raises(MemoryLimitExceeded, match="injected"):
            p.reserve("qf", 10)  # the Nth (2nd) reserve fails
        p.reserve("qf", 10)  # count exhausted
        p.reserve("other", 10)  # owner filter
    finally:
        faults.configure(None)


def test_fault_mem_pressure_shrinks_budget():
    p = MemoryPool(1 << 20)
    p.node_id = "worker-x"
    p.reserve("q1", 100)
    faults.configure(
        {"rules": [{"action": "mem_pressure", "node": "worker-x",
                    "budget": 150, "count": 1}]}
    )
    try:
        with pytest.raises(MemoryLimitExceeded):
            p.reserve("q2", 100)  # shrunk to 150: 100+100 over
        assert p.limit == 150
        p.reserve("q2", 40)  # still fits under the shrunken budget
    finally:
        faults.configure(None)


# --------------------------------------------------------- arbiter units


def _mk_arbiter(**cfg):
    base = {
        "memory.governance-enabled": "true",
        "query.max-memory-per-node": "1KB",
    }
    base.update(cfg)
    coord = CoordinatorServer(config=NodeConfig(base))
    # unit tests drive _decide() by hand: keep observe() side-effect
    # free so synthetic reports never dispatch real kills mid-setup
    coord.arbiter.enabled = False
    return coord, coord.arbiter


def _report(limit=1024, queries=None, blocked=None, spilled=0):
    return {
        "limit": limit,
        "reserved": sum(
            q["bytes"] for q in (queries or {}).values()
        ),
        "queries": queries or {},
        "blocked": blocked or [],
        "spilled_bytes": spilled,
    }


def _fake_query(coord, qid, state="RUNNING", create_time=None):
    from presto_tpu.server.coordinator import _Query

    q = _Query(qid, "select 1")
    q.state = state
    if create_time is not None:
        q.stats.create_time = create_time
    coord.queries[qid] = q
    return q


def test_arbiter_quota_math_per_node_and_cluster():
    coord, arb = _mk_arbiter(**{"query.max-memory": "1.5KB"})
    try:
        _fake_query(coord, "qa")
        _fake_query(coord, "qb")
        # qa: 1KB on two nodes (cluster 2KB > 1.5KB cap; per-node at
        # exactly the 1KB cap — not over it)
        # qb: 2KB on one node (over the 1KB per-node cap)
        arb.observe("w1", _report(queries={
            "qa": {"bytes": 1024, "peak": 1024},
        }))
        arb.observe("w2", _report(queries={
            "qa": {"bytes": 1024, "peak": 1024},
            "qb": {"bytes": 2048, "peak": 2048},
        }))
        decisions = {v: p for v, p, _r in arb._decide()}
        assert decisions["qa"] == "query.max-memory"
        assert decisions["qb"] == "query.max-memory-per-node"
        # claimed victims are latched: no duplicate kills next round
        assert arb._decide() == []
        arb.forget_query("qa")
        assert "qa" in {v for v, _p, _r in arb._decide()}
    finally:
        coord.shutdown()


def test_arbiter_policy_selection():
    coord, arb = _mk_arbiter()
    try:
        _fake_query(coord, "big", create_time=1.0)
        _fake_query(coord, "late", create_time=2.0)
        blocked = [{"owner": "big", "bytes": 512, "age_s": 9.0}]
        arb.observe("w1", _report(queries={
            "big": {"bytes": 900, "peak": 900},
            "late": {"bytes": 100, "peak": 100},
        }, blocked=blocked))
        # total-reservation: the largest cluster-wide holder dies
        assert arb._pick_victim(
            {"big": 900, "late": 100}, blocked,
            lambda qid: qid in coord.queries,
        ) == "big"
        arb.kill_policy = "last-admitted"
        assert arb._pick_victim(
            {"big": 900, "late": 100}, blocked,
            lambda qid: qid in coord.queries,
        ) == "late"
        # no running holder: the blocked owner is its own victim
        arb.kill_policy = "total-reservation"
        assert arb._pick_victim(
            {}, blocked, lambda qid: qid == "big"
        ) == "big"
    finally:
        coord.shutdown()


def test_arbiter_rejects_unknown_policy():
    with pytest.raises(ValueError, match="kill-policy"):
        ClusterMemoryArbiter(None, NodeConfig({
            "memory.kill-policy": "largest-gpu",
        }))


def test_arbiter_admission_high_water_hysteresis():
    coord, arb = _mk_arbiter(**{
        "memory.admission-high-water": "0.8",
        "memory.admission-low-water": "0.5",
    })
    try:
        arb.enabled = True
        # coordinator pool contributes 1KB capacity, worker 1KB more
        arb.observe("w1", _report(queries={
            "q": {"bytes": 1900, "peak": 1900},
        }))
        assert arb.admission_held() is True  # 1900/2048 > 0.8
        # hysteresis: dropping under high but above low stays held
        arb.observe("w1", _report(queries={
            "q": {"bytes": 1400, "peak": 1900},
        }))
        assert arb.admission_held() is True  # 0.68 in (0.5, 0.8)
        arb.observe("w1", _report(queries={
            "q": {"bytes": 100, "peak": 1900},
        }))
        assert arb.admission_held() is False  # below low water
        assert arb.pressure_subsided() is True
    finally:
        coord.shutdown()


def test_group_memory_folds_worker_reported_bytes():
    """Regression (historical under-accounting): resource-group quotas
    saw only coordinator-local bytes, so a distributed memory hog
    never tripped its group's softMemoryLimit."""
    coord = CoordinatorServer(
        config=NodeConfig({"memory.governance-enabled": "true"}),
        resource_groups={
            "rootGroups": [
                {"name": "adhoc", "hardConcurrencyLimit": 4,
                 "softMemoryLimit": "1KB"},
            ],
        },
    )
    try:
        q = _fake_query(coord, "qhog")
        q.resource_group = "adhoc"
        assert coord._group_memory("adhoc") == 0
        # every byte lives worker-side: the fold must still see it
        coord.arbiter.observe("w1", _report(queries={
            "qhog": {"bytes": 4096, "peak": 4096},
        }))
        assert coord._group_memory("adhoc") == 4096
        g = coord.resource_groups.groups["adhoc"]
        assert coord.resource_groups._over_memory(g) is True
    finally:
        coord.shutdown()


# ------------------------------------------------------ host-spill lane


def test_spill_round_trip_bit_identical():
    from presto_tpu import types as T
    from presto_tpu.exec.staging import (
        SplitCache,
        page_nbytes,
        stage_page,
    )

    schema = {"a": T.BIGINT, "s": T.VARCHAR}
    def mkpage(seed):
        from presto_tpu.connectors.tpch import DictColumn

        return stage_page(
            {
                "a": np.arange(seed, seed + 500, dtype=np.int64),
                "s": DictColumn(
                    ids=np.arange(500, dtype=np.int32) % 3,
                    values=np.array(["x", "y", "z"], object),
                ),
            },
            schema,
        )

    pool = MemoryPool(1 << 20)
    p1, p2 = mkpage(0), mkpage(7)
    budget = page_nbytes(p1) + 64
    c = SplitCache(budget_bytes=budget, pool=pool, spill_bytes=1 << 20)
    assert c.put("k1", p1)
    assert c.put("k2", p2)  # evicts k1 to the host spill store
    st = c.stats()
    assert st["spill_entries"] == 1 and st["spills"] == 1
    assert c.spill_used_bytes() > 0
    got = c.get("k1")  # restage from host RAM
    assert got is not None
    for b_got, b_ref in zip(got.blocks, p1.blocks):
        np.testing.assert_array_equal(
            np.asarray(b_got.data), np.asarray(b_ref.data)
        )
        assert b_got.dictionary == b_ref.dictionary
    assert c.stats()["restages"] == 1
    # accounting stays airtight: pool holds exactly the resident bytes
    assert pool.used_bytes("table-cache") == c.stats()["bytes"]
    c.clear()
    assert pool.used_bytes() == 0 and c.spill_used_bytes() == 0


def test_spilled_vs_unspilled_results_bit_identical():
    """End-to-end spill equivalence: a streamed query whose split
    batches cycle through a cache too small to hold them (every pass
    spills/restages) returns exactly the unspilled rows."""
    sql = (
        "select l_returnflag, count(*) c, sum(l_quantity) s "
        "from tpch.tiny.lineitem group by l_returnflag "
        "order by l_returnflag"
    )
    plain = LocalQueryRunner()
    expect = plain.execute(sql).rows()

    pool = MemoryPool(1 << 30)
    r = LocalQueryRunner(
        memory_pool=pool, staging_cache_bytes=1 << 20
    )
    r.split_cache.set_spill_budget(64 << 20)
    r.session.set("stream_split_cache", True)
    r.session.set("max_device_rows", 4096)  # force split streaming
    first = r.execute(sql).rows()
    # HBM pressure: the pool's pressure-hook path reclaims every
    # cached device byte — with the spill lane on, the pages offload
    # to host RAM instead of dropping
    freed = r.split_cache.evict_bytes(1 << 30)
    assert freed > 0
    st = r.split_cache.stats()
    assert st["spills"] > 0 and st["bytes"] == 0, st
    second = r.execute(sql).rows()  # restages from the host copies
    assert first == expect
    assert second == expect
    st = r.split_cache.stats()
    assert st["restages"] > 0, st


def test_runtime_memory_view_local_runner():
    pool = MemoryPool(1 << 30)
    r = LocalQueryRunner(memory_pool=pool)
    # stage a cacheable table first: its table-cache reservation must
    # show up as a holder row in the view
    r.execute("select count(*) c from tpch.tiny.region")
    rows = r.execute(
        "select node_id, query_id, state, reserved_bytes, limit_bytes "
        "from system.runtime.memory"
    ).rows()
    node_rows = [t for t in rows if t[1] == ""]
    assert node_rows and node_rows[0][0] == "local"
    assert node_rows[0][4] == 1 << 30
    holders = {t[1]: t for t in rows if t[2] == "RESERVED"}
    assert "table-cache" in holders, rows
    assert holders["table-cache"][3] > 0


# --------------------------------------------------- cluster acceptance


def _wait_workers(coord, n, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers never announced")


def _mk_cluster(tmp_path, n=2, extra=None, governance=True):
    cfg = {
        "announcement.interval-s": "0.1",
        "staging.cache-bytes": "0",
        "query.max-memory-per-node": "49152",
    }
    if governance:
        cfg.update({
            "memory.governance-enabled": "true",
            "memory.blocked-timeout-s": "0.2",
            "memory.reserve-block-max-s": "10",
        })
    cfg.update(extra or {})
    coord = CoordinatorServer(config=NodeConfig(dict(cfg))).start()
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=NodeConfig(dict(cfg))
        ).start()
        for _ in range(n)
    ]
    _wait_workers(coord, n)
    return coord, workers


def _teardown(coord, workers):
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


HUNGRY = "select sum(l_quantity) s from tpch.tiny.lineitem"
SMALL = "select count(*) c from tpch.tiny.region"


def test_chaos_memory_pressure_exact_victims(tmp_path):
    """The acceptance storm: concurrent memory-hungry + small queries
    on a deliberately tiny per-node budget. Exactly the arbiter-chosen
    victim(s) fail with MEMORY_PRESSURE (error names victim and
    policy), every other query completes with exact results, no
    reservation leaks, and the kill decision is journaled and visible
    in system.runtime.memory + memory.* metrics."""
    from presto_tpu.server.client import PrestoTpuClient, QueryFailed

    killed0 = int(REGISTRY.counter("memory.queries_killed").total)
    coord, ws = _mk_cluster(
        tmp_path,
        extra={"coordinator.journal-path": str(tmp_path / "journal")},
    )
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        expect_small = client.execute(SMALL).rows()
        results = {}
        lock = threading.Lock()

        def run(tag, sql):
            c = PrestoTpuClient(coord.uri, timeout_s=120)
            try:
                rows = c.execute(sql).rows()
                out = ("ok", rows)
            except QueryFailed as e:
                out = ("failed", str(e))
            with lock:
                results[tag] = out

        threads = [
            threading.Thread(target=run, args=(f"hungry{i}", HUNGRY))
            for i in range(2)
        ] + [
            threading.Thread(target=run, args=(f"small{i}", SMALL))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        # every hungry query is an arbiter victim: MEMORY_PRESSURE
        # naming victim + policy; every small query is exact
        for i in range(2):
            kind, detail = results[f"hungry{i}"]
            assert kind == "failed", results
            assert "MEMORY_PRESSURE" in detail, detail
            assert "policy total-reservation" in detail, detail
            assert "victim q_c" in detail, detail
        for i in range(3):
            assert results[f"small{i}"] == ("ok", expect_small), results
        # pools drain to zero after the storm (no leaked reservation)
        deadline = time.monotonic() + 5
        def drained():
            return all(
                w.memory_pool.used_bytes() == 0 for w in ws
            ) and coord.memory_pool.used_bytes() == 0
        while time.monotonic() < deadline and not drained():
            time.sleep(0.05)
        assert drained(), (
            [w.memory_pool.snapshot() for w in ws],
            coord.memory_pool.snapshot(),
        )
        # decision visible: system.runtime.memory, metrics, journal
        rows = client.execute(
            "select query_id, state from system.runtime.memory "
            "where state like 'KILLED%'"
        ).rows()
        assert len(rows) >= 1, rows
        assert all(r[1] == "KILLED (total-reservation)" for r in rows)
        assert (
            int(REGISTRY.counter("memory.queries_killed").total)
            > killed0
        )
        jdir = str(tmp_path / "journal")
        frames = ""
        for fn in os.listdir(jdir):
            with open(os.path.join(jdir, fn)) as f:
                frames += f.read()
        assert '"ev": "kill"' in frames
        assert "MEMORY_PRESSURE" not in frames or True  # reason text
    finally:
        _teardown(coord, ws)


def test_governance_disabled_is_legacy_fail_fast(tmp_path):
    """memory.governance-enabled=false: the same over-budget query
    fails with the pre-PR local-pool error shape (no MEMORY_PRESSURE,
    no kills, no blocked reservations, no spill)."""
    from presto_tpu.server.client import PrestoTpuClient, QueryFailed

    coord, ws = _mk_cluster(tmp_path, governance=False)
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        assert client.execute(SMALL).rows() == [(5,)]
        with pytest.raises(QueryFailed) as ei:
            client.execute(HUNGRY).rows()
        msg = str(ei.value)
        assert "MEMORY_PRESSURE" not in msg
        assert "exceeds pool limit" in msg or "MemoryLimitExceeded" in msg
        assert coord.arbiter.decisions == type(coord.arbiter.decisions)(
            maxlen=coord.arbiter.decisions.maxlen
        )
        assert all(
            w.memory_pool.block_timeout_s == 0.0 for w in ws
        )
        assert all(
            w.runner.split_cache.spill_budget == 0 for w in ws
        )
    finally:
        _teardown(coord, ws)


def test_victim_readmitted_under_query_retry(tmp_path):
    """retry_policy=QUERY: the killer's victim is re-admitted after
    pressure subsides, within the query_retry_count budget — each
    re-admission counts, and an incurably over-budget query still
    terminates with MEMORY_PRESSURE once the budget is spent."""
    from presto_tpu.server.client import PrestoTpuClient, QueryFailed

    readmit0 = int(
        REGISTRY.counter("memory.victims_readmitted").total
    )
    coord, ws = _mk_cluster(tmp_path, n=1)
    coord.local.session.set("retry_policy", "QUERY")
    coord.local.session.set("query_retry_count", 1)
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        with pytest.raises(QueryFailed) as ei:
            client.execute(HUNGRY).rows()
        # killed -> re-admitted once (pressure trivially subsided) ->
        # killed again -> budget spent -> MEMORY_PRESSURE surfaces
        assert "MEMORY_PRESSURE" in str(ei.value)
        assert (
            int(REGISTRY.counter("memory.victims_readmitted").total)
            == readmit0 + 1
        )
        # small queries still run fine afterwards
        assert client.execute(SMALL).rows() == [(5,)]
    finally:
        _teardown(coord, ws)


def test_worker_heartbeat_carries_memory_report(tmp_path):
    coord, ws = _mk_cluster(tmp_path, n=1)
    try:
        rep = ws[0]._memory_report()
        assert rep["limit"] == 49152
        assert set(rep) >= {
            "limit", "reserved", "queries", "blocked", "spilled_bytes",
        }
        # the status endpoint serves the same report
        from presto_tpu.server import rpc

        st = rpc.call_json("GET", ws[0].uri + "/v1/status")
        assert st["memory"]["limit"] == 49152
        # and the coordinator's arbiter has folded an observation
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if ws[0].node_id in coord.arbiter._live_reports():
                break
            time.sleep(0.05)
        assert ws[0].node_id in coord.arbiter._live_reports()
    finally:
        _teardown(coord, ws)


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
