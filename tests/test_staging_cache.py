"""Device-resident split cache + pipelined prefetch staging.

Covers the worker hot-path optimization end to end: LRU/byte-budget
semantics of :class:`presto_tpu.exec.staging.SplitCache` (enforced
through the memory accountant), cache-hit correctness vs fresh
staging, invalidation on writable-connector writes, prefetch-depth=0
equivalence plus the ``stage:prefetch``/``execute`` span overlap,
pipelined exchange pulls (``rpc.pull-depth``), and adaptive
exchange compression.
"""

import os
import time

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import (
    SplitCache,
    page_nbytes,
    prefetch_iter,
    stage_page,
)
from presto_tpu.session import NodeConfig, Session
from presto_tpu.utils.memory import MemoryPool


def _page(n=1024, fill=1):
    return stage_page(
        {"x": np.full(n, fill, np.int64)}, {"x": T.BIGINT}
    )


def _h(table):
    return TableHandle("tpch", "tiny", table)


# ------------------------------------------------- SplitCache semantics


def test_lru_eviction_respects_budget_and_pool():
    pool = MemoryPool(1 << 20)
    page = _page()
    nbytes = page_nbytes(page)
    cache = SplitCache(budget_bytes=int(nbytes * 2.5), pool=pool)
    k1, k2, k3, k4 = [(_h("a"), i) for i in range(4)]
    assert cache.put(k1, _page(fill=1))
    assert cache.put(k2, _page(fill=2))
    # both fit; the pool's shared owner carries exactly the cache bytes
    assert pool.used_bytes(SplitCache.OWNER) == cache.used_bytes()
    assert cache.put(k3, _page(fill=3))  # evicts k1 (LRU)
    assert cache.evictions == 1
    assert cache.used_bytes() <= cache.budget
    assert pool.used_bytes(SplitCache.OWNER) == cache.used_bytes()
    assert cache.get(k1) is None
    assert cache.get(k2) is not None  # refreshes k2
    assert cache.put(k4, _page(fill=4))  # now evicts k3, not k2
    assert cache.get(k3) is None
    assert cache.get(k2) is not None
    stats = cache.stats()
    assert stats["evictions"] == 2
    assert stats["bytes"] == pool.used_bytes(SplitCache.OWNER)


def test_oversized_entry_never_cached():
    cache = SplitCache(budget_bytes=100)
    assert not cache.put((_h("a"), 0), _page())
    assert cache.used_bytes() == 0


def test_cache_fill_never_kills_a_query():
    """try_reserve discipline: a full pool means "not cached", not a
    kill-largest eviction of a running query's reservation."""
    pool = MemoryPool(10_000)
    pool.reserve("q_running", 9_000)
    cache = SplitCache(budget_bytes=1 << 20, pool=pool)
    assert not cache.put((_h("a"), 0), _page())  # 8KB won't fit
    assert pool.used_bytes("q_running") == 9_000
    assert cache.used_bytes() == 0


def test_query_reservation_reclaims_cache_under_pressure():
    """A query's raising reserve evicts droppable cache bytes (the
    MemoryPool pressure hook) instead of failing or killing a query
    while gigabytes of cache sit idle."""
    page = _page()
    nbytes = page_nbytes(page)
    pool = MemoryPool(int(nbytes * 3.5))
    cache = SplitCache(budget_bytes=1 << 20, pool=pool)
    for i in range(3):
        assert cache.put((_h("a"), i), _page(fill=i))
    # pool nearly full of cache; a query needs 2 pages' worth
    pool.reserve("q_live", int(nbytes * 2))
    assert pool.used_bytes("q_live") == int(nbytes * 2)
    assert cache.stats()["entries"] <= 1  # LRU entries yielded
    assert (
        pool.used_bytes(SplitCache.OWNER) == cache.used_bytes()
    )


def test_pinned_entries_survive_pressure_eviction():
    """An entry serving an EXECUTING batch is pinned: eviction must
    not release its pool accounting while the page is live on device
    (over-commit). Unpinning makes it evictable again."""
    page = _page()
    nbytes = page_nbytes(page)
    pool = MemoryPool(int(nbytes * 4.5))
    cache = SplitCache(budget_bytes=1 << 20, pool=pool)
    keys = [(_h("a"), i) for i in range(3)]
    for i, k in enumerate(keys):
        assert cache.put(k, _page(fill=i))
    assert cache.get(keys[0], pin=True) is not None
    # pressure for ~2 pages: LRU order would take k0 first, but it is
    # pinned — k1/k2 go instead
    pool.reserve("q_live", int(nbytes * 3))
    assert cache.get(keys[0]) is not None  # pinned entry survived
    assert cache.get(keys[1]) is None and cache.get(keys[2]) is None
    assert pool.used_bytes(SplitCache.OWNER) == cache.used_bytes()
    # fully pinned cache cannot satisfy further pressure: reserve fails
    with pytest.raises(Exception):
        pool.reserve("q_more", int(nbytes * 2))
    cache.unpin(keys[0])
    pool.reserve("q_more", int(nbytes * 1.2))  # now evictable
    assert cache.stats()["entries"] == 0


def test_put_does_not_evict_when_pool_reservation_fails():
    """try_reserve failure must not have emptied the cache first."""
    page = _page()
    nbytes = page_nbytes(page)
    pool = MemoryPool(int(nbytes * 2.5))
    cache = SplitCache(budget_bytes=int(nbytes * 1.5), pool=pool)
    assert cache.put((_h("a"), 0), _page())
    pool.reserve("q_live", int(nbytes * 1.2))  # pool now tight
    assert not cache.put((_h("a"), 1), _page())
    assert cache.stats()["entries"] == 1  # existing entry survived


def test_invalidate_releases_reservations():
    pool = MemoryPool(1 << 20)
    cache = SplitCache(budget_bytes=1 << 20, pool=pool)
    cache.put((_h("a"), 0), _page())
    cache.put((_h("a"), 1), _page())
    cache.put((_h("b"), 0), _page())
    assert cache.invalidate(_h("a")) == 2
    assert cache.stats()["entries"] == 1
    assert pool.used_bytes(SplitCache.OWNER) == cache.used_bytes()


# ------------------------------------------- runner integration (hits)


def test_repeated_query_hits_cache_and_skips_connector():
    r = LocalQueryRunner()
    conn = r.catalogs.get("tpch")
    calls = []
    orig = conn.create_page_source

    def spy(split, columns):
        calls.append(split)
        return orig(split, columns)

    q = "select count(*) as c, sum(r_regionkey) as s from tpch.tiny.region"
    conn.create_page_source = spy
    try:
        first = r.execute(q)
        assert len(calls) > 0
        calls.clear()
        second = r.execute(q)
        assert calls == [], "warm run must not touch the connector"
    finally:
        conn.create_page_source = orig
    assert first.rows() == second.rows()
    assert r.split_cache.hits >= 1
    # per-query stats carry the hit count
    warm_qs = r.history.snapshot()[-1]
    assert warm_qs.staging_cache_hits >= 1


def test_cache_budget_enforced_through_accountant_under_load():
    """With a budget far below the working set, the cache never
    exceeds staging.cache-bytes (asserted via the memory pool) and
    eviction keeps queries correct."""
    pool = MemoryPool(1 << 30)
    budget = 200_000  # region+nation fit; lineitem columns do not
    r = LocalQueryRunner(memory_pool=pool, staging_cache_bytes=budget)
    queries = [
        "select count(*) as c from tpch.tiny.region",
        "select count(*) as c from tpch.tiny.nation",
        "select count(*) as c from tpch.tiny.supplier",
        "select sum(l_quantity) as s from tpch.tiny.lineitem",
        "select count(*) as c from tpch.tiny.region",
    ]
    expect = [(5,)], [(25,)], [(100,)], None, [(5,)]
    for q, exp in zip(queries * 2, list(expect) * 2):
        res = r.execute(q)
        if exp is not None:
            assert res.rows() == exp
        assert r.split_cache.used_bytes() <= budget
        assert pool.used_bytes(SplitCache.OWNER) <= budget
        assert (
            pool.used_bytes(SplitCache.OWNER)
            == r.split_cache.used_bytes()
        )


def test_memory_connector_write_invalidates_cache():
    from presto_tpu.connectors import create_connector

    r = LocalQueryRunner()
    r.catalogs.register("mem", create_connector("memory"))
    r.execute("create table mem.default.t (x bigint)")
    r.execute("insert into mem.default.t values (1), (2)")
    q = "select x from mem.default.t order by x"
    assert r.execute(q).rows() == [(1,), (2,)]
    handle = TableHandle("mem", "default", "t")
    assert any(
        k[0] == handle for k in r.split_cache._entries
    ), "memory-connector page should be cached after a scan"
    r.execute("insert into mem.default.t values (3)")
    assert not any(
        k[0] == handle for k in r.split_cache._entries
    ), "a write must invalidate the table's cached pages"
    assert r.execute(q).rows() == [(1,), (2,), (3,)]
    r.execute("delete from mem.default.t where x = 2")
    assert r.execute(q).rows() == [(1,), (3,)]


# -------------------------------------------------- prefetch pipeline


def test_prefetch_iter_orders_and_depth_zero_equivalence():
    items = list(range(7))
    serial = list(prefetch_iter(items, lambda x: x * x, 0))
    piped = list(prefetch_iter(items, lambda x: x * x, 2))
    assert serial == piped == [x * x for x in items]


def test_prefetch_iter_propagates_errors():
    def load(x):
        if x == 3:
            raise ValueError("boom")
        return x

    got = []
    with pytest.raises(ValueError, match="boom"):
        for v in prefetch_iter(range(6), load, 2):
            got.append(v)
    assert got == [0, 1, 2]


def _streamed_runner(depth):
    return LocalQueryRunner(
        session=Session(
            properties={
                "max_device_rows": 16_384,
                "page_capacity": 4_096,
                "staging_prefetch_depth": depth,
            }
        )
    )


STREAMED_Q = (
    "select l_returnflag, sum(l_quantity) as s, count(*) as c "
    "from tpch.tiny.lineitem group by l_returnflag order by l_returnflag"
)


def test_prefetch_depth_zero_bit_identical():
    rows0 = _streamed_runner(0).execute(STREAMED_Q).rows()
    rows2 = _streamed_runner(2).execute(STREAMED_Q).rows()
    assert rows0 == rows2


def test_prefetch_spans_overlap_execute():
    """The trace of a multi-split scan shows stage:prefetch spans
    overlapping the open execute span (the compute/transfer overlap
    EXPLAIN ANALYZE is supposed to make visible)."""
    r = _streamed_runner(2)
    r.execute(STREAMED_Q)
    qs = r.history.snapshot()[-1]
    spans = qs.trace.spans()
    execute = next(s for s in spans if s.name == "execute")
    prefetch = [s for s in spans if s.name == "stage:prefetch"]
    assert prefetch, "prefetch staging must be traced"
    overlapping = [
        s
        for s in prefetch
        if s.start < execute.end and execute.start < s.end
    ]
    assert overlapping, "prefetch spans must overlap execution"


# ------------------------------------- worker hot path (distributed)


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


def test_worker_warm_task_reports_cache_hits():
    from presto_tpu.server import CoordinatorServer, WorkerServer
    from presto_tpu.server.client import PrestoTpuClient

    coord = CoordinatorServer().start()
    w = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        assert w.runner.session.get("stream_split_cache") is True
        _wait_workers(coord, 1)
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        q = "select count(*) as c from tpch.tiny.orders"
        cold = client.execute(q)
        assert cold.rows() == [(15000,)]
        warm = client.execute(q)
        assert warm.rows() == [(15000,)]
        info = client.query_info(warm.query_id)
        hits = sum(
            t.get("staging_cache_hits", 0)
            for st in info["stages"]
            for t in st["tasks"]
        )
        assert hits > 0, "warm task must serve splits from the cache"
        assert info.get("staging_cache_hits", 0) > 0  # query rollup
    finally:
        w.shutdown(graceful=False)
        coord.shutdown()


def test_worker_cache_disabled_by_zero_budget():
    from presto_tpu.server import WorkerServer

    w = WorkerServer(
        config=NodeConfig({"staging.cache-bytes": "0"})
    )
    try:
        assert w.runner.session.get("stream_split_cache") is False
        assert w.runner.split_cache.budget == 0
    finally:
        w.shutdown(graceful=False)


# ----------------------------------------- pipelined exchange pulls


@pytest.mark.parametrize("pull_depth", [1, 2, 3])
def test_pull_depth_results_identical(monkeypatch, pull_depth):
    """Multi-page pulls return every page exactly once at any depth
    (the X-Ack floor keeps speculative requests from freeing
    unconsumed pages)."""
    from presto_tpu.server import CoordinatorServer, WorkerServer
    from presto_tpu.server import worker as worker_mod
    from presto_tpu.server.client import PrestoTpuClient

    monkeypatch.setattr(worker_mod, "PAGE_ROWS", 512)
    coord = CoordinatorServer(
        config=NodeConfig({"rpc.pull-depth": str(pull_depth)})
    ).start()
    w = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        _wait_workers(coord, 1)
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        res = client.execute(
            "select c_custkey from tpch.tiny.customer"
        )
        got = sorted(r[0] for r in res.rows())
        assert len(got) == 1500
        assert got == list(range(1, 1501))
    finally:
        w.shutdown(graceful=False)
        coord.shutdown()


# ------------------------------------------- adaptive compression


def test_wire_small_buffer_ships_raw():
    from presto_tpu.server import pages_wire

    data = np.arange(4, dtype=np.int64)
    buf = pages_wire.serialize_page([("x", data, None, T.BIGINT, None)], 4)
    import json as _json
    import struct

    (hlen,) = struct.unpack_from("<I", buf, 4)
    header = _json.loads(buf[8 : 8 + hlen].decode())
    col = header["columns"][0]
    assert col["enc"] == "raw"
    assert col["comp_size"] == col["raw_size"]
    payload, schema, n = pages_wire.deserialize_page(buf)
    assert n == 4
    np.testing.assert_array_equal(payload["x"], data)


def test_wire_compressible_buffer_still_zlib():
    from presto_tpu.server import pages_wire

    data = np.zeros(100_000, dtype=np.int64)
    buf = pages_wire.serialize_page(
        [("x", data, None, T.BIGINT, None)], len(data)
    )
    import json as _json
    import struct

    (hlen,) = struct.unpack_from("<I", buf, 4)
    col = _json.loads(buf[8 : 8 + hlen].decode())["columns"][0]
    assert col["enc"] == "zlib"
    assert col["comp_size"] < col["raw_size"]
    payload, _schema, _n = pages_wire.deserialize_page(buf)
    np.testing.assert_array_equal(payload["x"], data)


def test_wire_incompressible_buffer_skips_zlib():
    from presto_tpu.server import pages_wire

    rng = np.random.default_rng(7)
    data = rng.integers(0, 2**62, size=100_000, dtype=np.int64)
    buf = pages_wire.serialize_page(
        [("x", data, None, T.BIGINT, None)], len(data)
    )
    import json as _json
    import struct

    (hlen,) = struct.unpack_from("<I", buf, 4)
    col = _json.loads(buf[8 : 8 + hlen].decode())["columns"][0]
    assert col["enc"] == "raw"
    payload, _schema, _n = pages_wire.deserialize_page(buf)
    np.testing.assert_array_equal(payload["x"], data)


def test_wire_legacy_frame_without_enc_decodes():
    """Backward compat: a header with no enc fields reads as zlib."""
    import json as _json
    import struct
    import zlib

    from presto_tpu.server import pages_wire

    data = np.arange(1000, dtype=np.int64)
    raw = data.tobytes()
    comp = zlib.compress(raw, 1)
    header = {
        "nrows": 1000,
        "columns": [
            {
                "name": "x",
                "type": "bigint",
                "np_dtype": data.dtype.str,
                "comp_size": len(comp),
                "raw_size": len(raw),
                "crc32": zlib.crc32(raw),
            }
        ],
    }
    hj = _json.dumps(header).encode()
    buf = b"".join([b"PTP1", struct.pack("<I", len(hj)), hj, comp])
    payload, schema, n = pages_wire.deserialize_page(buf)
    assert n == 1000
    np.testing.assert_array_equal(payload["x"], data)


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
