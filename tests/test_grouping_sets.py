"""GROUPING SETS / ROLLUP / CUBE (reference: presto grouping-set
queries; SURVEY.md §2.1 planner GroupIdNode).

The engine and the sqlite oracle share the desugar rewrite
(sql/grouping_sets.py), so oracle agreement alone cannot catch a bug
in the rewrite itself. This suite therefore also checks:
  * a HAND-WRITTEN UNION ALL expansion (independent of the rewrite)
    agrees with the rollup form on the engine and on the oracle, and
  * pinned literal expectations over a VALUES relation (independent
    arithmetic, no generators).
"""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.plan.planner import PlanningError
from presto_tpu.verifier import SqliteOracle, verify_query


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


CORPUS = {
    "rollup2": (
        "select l_returnflag, l_linestatus, sum(l_quantity) as s "
        "from tpch.tiny.lineitem "
        "group by rollup (l_returnflag, l_linestatus) order by 1, 2"
    ),
    "cube2": (
        "select l_returnflag, l_linestatus, count(*) as c "
        "from tpch.tiny.lineitem "
        "group by cube (l_returnflag, l_linestatus) order by 1, 2"
    ),
    "sets_explicit": (
        "select l_returnflag, l_linestatus, sum(l_quantity) as s "
        "from tpch.tiny.lineitem group by grouping sets "
        "((l_returnflag, l_linestatus), (l_linestatus), ()) "
        "order by 1, 2"
    ),
    "mixed_plain_rollup": (
        "select l_returnflag, l_linestatus, sum(l_quantity) as s "
        "from tpch.tiny.lineitem "
        "group by l_returnflag, rollup (l_linestatus) order by 1, 2"
    ),
    "grouping_fn": (
        "select l_returnflag, l_linestatus, "
        "grouping(l_returnflag, l_linestatus) as g, count(*) as c "
        "from tpch.tiny.lineitem "
        "group by rollup (l_returnflag, l_linestatus) order by 1, 2, 3"
    ),
    "having_on_rollup": (
        "select l_returnflag, sum(l_quantity) as s "
        "from tpch.tiny.lineitem group by rollup (l_returnflag) "
        "having sum(l_quantity) > 1000 order by 1"
    ),
    "window_over_rollup": (
        "select l_returnflag, sum(l_quantity) as s, "
        "rank() over (order by sum(l_quantity) desc) as r "
        "from tpch.tiny.lineitem group by rollup (l_returnflag) "
        "order by 1"
    ),
}


@pytest.mark.parametrize("name", sorted(CORPUS))
def test_grouping_sets_oracle(name, runner, oracle):
    diff = verify_query(runner, oracle, CORPUS[name], rel_tol=1e-6)
    assert diff is None, f"{name}: {diff}"


def test_rollup_matches_hand_expansion(runner, oracle):
    """The rewrite's output semantics checked against an expansion
    written BY HAND (three plain GROUP BY branches + NULL padding) —
    this is the independence check the shared-desugar oracle diff
    cannot provide."""
    rollup = (
        "select l_returnflag, l_linestatus, sum(l_quantity) as s "
        "from tpch.tiny.lineitem "
        "group by rollup (l_returnflag, l_linestatus)"
    )
    hand = (
        "select l_returnflag, l_linestatus, sum(l_quantity) as s "
        "from tpch.tiny.lineitem group by l_returnflag, l_linestatus "
        "union all "
        "select l_returnflag, null, sum(l_quantity) "
        "from tpch.tiny.lineitem group by l_returnflag "
        "union all "
        "select null, null, sum(l_quantity) from tpch.tiny.lineitem"
    )
    ours = sorted(
        runner.execute(rollup).rows(),
        key=lambda r: (str(r[0]), str(r[1])),
    )
    expanded = sorted(
        runner.execute(hand).rows(),
        key=lambda r: (str(r[0]), str(r[1])),
    )
    assert len(ours) == len(expanded)
    for a, b in zip(ours, expanded):
        assert a[:2] == b[:2]
        assert abs(a[2] - b[2]) < 1e-6 * max(1.0, abs(a[2]))
    # and the hand expansion itself is oracle-verified (sqlite runs it
    # natively, no shared rewrite in the loop)
    diff = verify_query(runner, oracle, hand, rel_tol=1e-6)
    assert diff is None, diff


def test_rollup_pinned_values(runner):
    """Fully independent arithmetic over a VALUES relation."""
    rows = runner.execute(
        "select k, sum(v) as s, grouping(k) as g "
        "from (values ('a', 1), ('a', 2), ('b', 10)) as t(k, v) "
        "group by rollup (k) order by k"
    ).rows()
    assert rows == [("a", 3, 0), ("b", 10, 0), (None, 13, 1)]


def test_cube_pinned_values(runner):
    rows = runner.execute(
        "select a, b, count(*) as c from "
        "(values (1, 1), (1, 2), (2, 1)) as t(a, b) "
        "group by cube (a, b) order by a, b"
    ).rows()
    assert rows == [
        (1, 1, 1),
        (1, 2, 1),
        (1, None, 2),
        (2, 1, 1),
        (2, None, 1),
        (None, 1, 2),
        (None, 2, 1),
        (None, None, 3),
    ]


def test_grouping_bitmask_pinned(runner):
    """grouping(a, b): a is the HIGH bit (Presto semantics)."""
    rows = runner.execute(
        "select a, b, grouping(a, b) as g from "
        "(values (1, 2)) as t(a, b) "
        "group by grouping sets ((a, b), (a), (b), ()) order by g"
    ).rows()
    assert rows == [
        (1, 2, 0),
        (1, None, 1),
        (None, 2, 2),
        (None, None, 3),
    ]


def test_grouping_sets_cap(runner):
    with pytest.raises(PlanningError, match="grouping sets exceed"):
        runner.execute(
            "select count(*) as c from tpch.tiny.nation group by "
            "cube (n_nationkey, n_name, n_regionkey, n_comment, "
            "n_nationkey, n_name, n_regionkey)"
        )


def test_concat_operator(runner, oracle):
    """|| at Presto precedence (below +/-), desugared to concat()."""
    assert runner.execute("select 'a' || 'b' || 'c' as x").rows() == [
        ("abc",)
    ]
    diff = verify_query(
        runner,
        oracle,
        "select n_name || '!' as x from tpch.tiny.nation order by 1",
    )
    assert diff is None, diff


def test_union_null_column_adopts_type(runner):
    """A bare NULL-literal union column takes the other terms' type
    (reference: UNKNOWN coercion) — the shape every grouping-set
    branch emits for absent group columns."""
    assert runner.execute(
        "select 'a' as x union all select null as x"
    ).rows() == [("a",), (None,)]
    assert runner.execute(
        "select x, count(*) as c from (select null as x union all "
        "select 'a' as x union all select 'a' as x) t "
        "group by x order by x"
    ).rows() == [("a", 2), (None, 1)]
