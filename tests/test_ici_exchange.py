"""ICI-native collective shuffle (the unified exchange SPI).

The scheduler plans partitioned join/agg/distinct exchanges between
co-located workers (same announced slice) as device-to-device
transfers through the in-slice segment — zero serialization, zero
zlib, zero HTTP on those edges — while cross-slice edges, recovery,
and drain keep the serialized wire + spool.

Pinned here:
- the DEVICE bucket hash == the HOST wire hash, bit-for-bit (mixed
  transports of one logical producer must partition identically or
  rows are lost across partitions);
- ICI-vs-HTTP result equality for partitioned join / shuffled agg /
  distinct on the 8-virtual-device CPU mesh, with the ICI window
  moving ZERO bytes through the pages_wire shuffle;
- transport selection rules (scheduler-owned);
- chaos: kill a co-located worker mid-join under retry_policy=TASK —
  the lost partitions recover over the HTTP/spool ladder with zero
  failed queries; drain-under-load still loses nothing;
- the compression-floor satellite: sub-floor buffers ship raw with
  no ratio probe, counted identically on both producer entry points.
"""

import threading
import time

import numpy as np
import pytest

from presto_tpu.server import (
    CoordinatorServer,
    PrestoTpuClient,
    WorkerServer,
)
from presto_tpu.server import exchange_spi, rpc, task_ids
from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY


JOIN_SQL = (
    "select o_orderpriority, count(*) as n, "
    "sum(l_extendedprice) as v "
    "from tpch.tiny.orders, tpch.tiny.lineitem "
    "where o_orderkey = l_orderkey "
    "group by o_orderpriority order by o_orderpriority"
)
AGG_SQL = (
    "select l_returnflag, l_linestatus, sum(l_quantity) as q, "
    "count(*) as n from tpch.tiny.lineitem "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
DISTINCT_SQL = (
    "select distinct l_suppkey from tpch.tiny.lineitem "
    "order by l_suppkey limit 50"
)


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


def _mk_cluster(n=3, cfg=None):
    cfg = dict(cfg or {})
    coord = CoordinatorServer(config=NodeConfig(dict(cfg))).start()
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=NodeConfig(dict(cfg))
        ).start()
        for _ in range(n)
    ]
    _wait_workers(coord, n)
    return coord, workers


def _teardown(coord, workers):
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def _counter(name):
    return REGISTRY.counter(name).total


# ------------------------------------------------ device == host hash


def test_device_bucket_hash_matches_host_wire_hash():
    """THE correctness contract: parallel.exchange.bucket_dest must
    assign every row the same partition as exec.streaming._bucket_of.
    Mixed attempts of one logical producer may run on either
    transport, and merge tasks pick attempts per-partition
    independently — disagreement loses or duplicates rows."""
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.exec import streaming as S
    from presto_tpu.exec.staging import MaskedColumn
    from presto_tpu.page import Block, Dictionary, Page
    from presto_tpu.parallel import exchange as X

    rng = np.random.default_rng(7)
    n, cap = 900, 1024
    ints = rng.integers(-5000, 5000, n).astype(np.int64)
    flts = rng.normal(size=n)
    flts[::7] = 0.0
    flts[::11] = -0.0  # -0.0 must hash like +0.0
    vals = np.array(sorted({"a", "bb", "ccc", "dddd", "e"}), object)
    ids = rng.integers(0, len(vals), n).astype(np.int32)
    valid = rng.random(n) > 0.15  # NULLs hash to one bucket
    limbs = rng.integers(-2**40, 2**40, size=(n, 2)).astype(np.int64)

    payload = {
        "k": ints,
        "f": flts,
        "s": DictColumn(ids=ids, values=vals),
        "m": MaskedColumn(data=ints.copy(), valid=valid),
        "d": limbs,
    }
    keys = ["k", "f", "s", "m", "d"]
    host = S._bucket_of(payload, keys, n, 7)

    def pad(a, tail=()):
        out = np.zeros((cap,) + tail, a.dtype)
        out[:n] = a
        return out

    dic = Dictionary(vals)
    page = Page(
        blocks=(
            Block(data=jnp.asarray(pad(ints)), valid=None,
                  dtype=T.BIGINT),
            Block(data=jnp.asarray(pad(flts)), valid=None,
                  dtype=T.DOUBLE),
            Block(data=jnp.asarray(pad(ids)), valid=None,
                  dtype=T.VARCHAR, dictionary=dic),
            Block(data=jnp.asarray(pad(ints)),
                  valid=jnp.asarray(pad(valid)), dtype=T.BIGINT),
            Block(data=jnp.asarray(pad(limbs, (2,))), valid=None,
                  dtype=T.decimal(30, 2)),
        ),
        num_valid=jnp.asarray(n, jnp.int32),
        names=("k", "f", "s", "m", "d"),
    )
    crc = {"s": X.wire_crc_table(dic)}
    dest = X.bucket_dest(
        X.strip_dictionaries(page), crc, jnp.asarray(7), tuple(keys)
    )
    assert np.array_equal(
        np.asarray(dest)[:n], host.astype(np.int32)
    ), "device bucket hash diverged from the host wire hash"
    counts = np.asarray(X.ici_partition_counts(page, dest))
    assert counts[:7].sum() == n and counts[7:].sum() == 0


# ------------------------------------------------ transport selection


def test_select_exchange_transport_rules():
    from presto_tpu import types as T
    from presto_tpu.server.scheduler import select_exchange_transport

    class W:
        def __init__(self, slice_id, state="ACTIVE"):
            self.slice_id = slice_id
            self.state = state

    same = [W("s1"), W("s1"), W("s1")]
    schema = {"a": T.BIGINT, "b": T.VARCHAR}
    assert select_exchange_transport(same, True, (schema,)) == "s1"
    # the gate off, mixed slices, unannounced topology, a DRAINING
    # peer, or a nested-type schema all keep the HTTP wire
    assert select_exchange_transport(same, False, (schema,)) == ""
    assert (
        select_exchange_transport([W("s1"), W("s2")], True, (schema,))
        == ""
    )
    assert select_exchange_transport([W(""), W("")], True, (schema,)) == ""
    assert (
        select_exchange_transport(
            [W("s1"), W("s1", state="DRAINING")], True, (schema,)
        )
        == ""
    )
    nested = {"a": T.array(T.BIGINT)}
    assert select_exchange_transport(same, True, (schema, nested)) == ""
    assert select_exchange_transport([], True, (schema,)) == ""


def test_fragment_spec_ici_slice_wire_roundtrip():
    from presto_tpu.plan import nodes as N
    from presto_tpu.server.protocol import FragmentSpec

    from presto_tpu import types as T

    root = N.ValuesNode(schema=(("a", T.BIGINT),))
    spec = FragmentSpec(
        task_id="q.prod.0.a0", query_id="q", fragment=root,
        partition_scan=-1, split_start=0, split_end=0,
        n_partitions=4, partition_keys=("a",), ici_slice="cpu-123",
    )
    back = FragmentSpec.from_json(spec.to_json())
    assert back.ici_slice == "cpu-123"
    # absent on old wire frames -> "" (HTTP), back-compatible
    d = spec.to_json()
    del d["ici_slice"]
    assert FragmentSpec.from_json(d).ici_slice == ""


# ------------------------------------------------ the equality battery


def test_ici_vs_http_battery_join_agg_distinct():
    """One cluster, each statement run under BOTH transports via the
    session override: results must match exactly, the ICI window must
    move zero bytes through the pages_wire shuffle, and in-slice edges
    + elided bytes must be counted. Also pins: slice discovery, the
    exchange.ici caches row, segment drained after DELETE."""
    coord, ws = _mk_cluster(3, {"exchange.ici-enabled": "true"})
    try:
        # slice discovery: every in-process worker announces the same
        # non-empty slice
        slices = {
            w.slice_id for w in coord.active_workers()
        }
        assert len(slices) == 1 and "" not in slices

        client = PrestoTpuClient(coord.uri, timeout_s=300)
        client.execute(
            "set session join_distribution_type = PARTITIONED"
        )
        for sql in (AGG_SQL, DISTINCT_SQL, JOIN_SQL):
            client.execute(
                "set session exchange_ici_enabled = false"
            )
            h0 = _counter("exchange.http_shuffle_bytes")
            rows_http = [tuple(r) for r in client.execute(sql).rows()]
            assert _counter("exchange.http_shuffle_bytes") > h0, sql

            client.execute("set session exchange_ici_enabled = true")
            h1 = _counter("exchange.http_shuffle_bytes")
            e1 = _counter("exchange.ici_edges")
            b1 = _counter("exchange.ici_bytes_elided")
            rows_ici = [tuple(r) for r in client.execute(sql).rows()]
            assert rows_ici == rows_http, f"transport changed answers: {sql}"
            assert _counter("exchange.http_shuffle_bytes") == h1, (
                f"ICI window moved bytes through pages_wire: {sql}"
            )
            assert _counter("exchange.ici_edges") > e1, sql
            assert _counter("exchange.ici_bytes_elided") > b1, sql

        # the win is observable: exchange.ici row in runtime.caches
        res = client.execute(
            "select cache, hits from system.runtime.caches "
            "where cache = 'exchange.ici'"
        )
        rows = [tuple(r) for r in res.rows()]
        assert len(rows) == 1 and rows[0][1] > 0
        # shuffle partitions must not outlive their queries: the
        # segment drains once tasks are DELETEd
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if exchange_spi.SEGMENT.stats()["entries"] == 0:
                break
            time.sleep(0.05)
        assert exchange_spi.SEGMENT.stats()["entries"] == 0
    finally:
        _teardown(coord, ws)


def test_ici_default_off_is_bit_exact_http():
    """No config key -> no segment publish, no ICI counters, specs
    carry no slice: the legacy HTTP shuffle, bit-exact."""
    coord, ws = _mk_cluster(2)
    try:
        e0 = _counter("exchange.ici_edges")
        b0 = _counter("exchange.ici_bytes_elided")
        f0 = _counter("exchange.ici_fallbacks")
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        expected = [
            tuple(r) for r in coord.local.execute(AGG_SQL).rows()
        ]
        assert [
            tuple(r) for r in client.execute(AGG_SQL).rows()
        ] == expected
        assert _counter("exchange.ici_edges") == e0
        assert _counter("exchange.ici_bytes_elided") == b0
        assert _counter("exchange.ici_fallbacks") == f0
    finally:
        _teardown(coord, ws)


# ----------------------------------------------------------- recovery


def test_chaos_kill_colocated_worker_mid_join_falls_back(tmp_path):
    """THE acceptance chaos test: kill one co-located worker mid
    multi-stage join with ICI on under retry_policy=TASK. The dead
    worker's device pages are gone (segment entries discarded, as a
    real crash would lose them) — the rescheduled merge recovers its
    partitions over the HTTP/spool ladder, with zero failed queries
    and upstream producers NOT re-run."""
    cfg = {
        "exchange.ici-enabled": "true",
        "exchange.spool-path": str(tmp_path / "spool"),
        "exchange.spool-bytes": "64MB",
        "retry-policy": "TASK",
    }
    coord, ws = _mk_cluster(2, cfg)
    coord.local.session.set("retry_policy", "TASK")
    try:
        expected = [
            tuple(r) for r in coord.local.execute(JOIN_SQL).rows()
        ]
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        faults.configure(
            {
                "seed": 2,
                "rules": [
                    {"action": "delay", "task": ".prod.",
                     "delay_s": 0.05},
                    {"action": "delay", "task": ".merge.",
                     "delay_s": 0.8},
                ],
            }
        )
        out, errs = {}, []

        def run():
            try:
                out["res"] = client.execute(JOIN_SQL)
            except Exception as e:
                errs.append(e)

        def seal_observed():
            for w in ws:
                with w._lock:
                    tasks = list(w.tasks.values())
                for t in tasks:
                    if t.spec.partition_scan < 0 and t.sources_done:
                        return True
            return False

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not seal_observed():
            time.sleep(0.002)
        assert seal_observed(), "producer stage never sealed"
        victim = ws[0]
        victim._fault_kill()  # dead sockets, no drain
        # a real crash loses the victim's device memory: drop its
        # segment entries so recovery must take the HTTP/spool ladder
        with victim._lock:
            doomed = list(victim.tasks)
        for tid in doomed:
            exchange_spi.SEGMENT.discard(tid)
        t.join(120)
        assert not errs, f"query failed despite TASK recovery: {errs}"
        assert [tuple(r) for r in out["res"].rows()] == expected

        info = client.query_info(out["res"].query_id)
        assert info["task_recoveries"] >= 1
        # upstream producer stage not re-run: one attempt per logical
        stages = {st["stage_id"]: st for st in info["stages"]}
        prod = next(
            st for st in stages.values() if st["kind"] == "producer"
        )
        by_logical = {}
        for tk in prod["tasks"]:
            by_logical.setdefault(
                task_ids.logical_key(tk["task_id"]), []
            ).append(tk)
        for lk, attempts in by_logical.items():
            assert len(attempts) == 1, f"producer {lk} re-ran"
    finally:
        _teardown(coord, ws)


def test_drain_under_load_with_ici_zero_failures(tmp_path):
    """Drain composes: a DRAINING worker's ICI edges degrade to HTTP
    (segment entries materialize into serialized buffers), the query
    completes with zero failures, and the drained worker exits."""
    cfg = {
        "exchange.ici-enabled": "true",
        "exchange.spool-path": str(tmp_path / "spool"),
        "retry-policy": "TASK",
    }
    coord, ws = _mk_cluster(2, cfg)
    coord.local.session.set("retry_policy", "TASK")
    try:
        expected = [
            tuple(r) for r in coord.local.execute(JOIN_SQL).rows()
        ]
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        faults.configure(
            {
                "seed": 5,
                "rules": [
                    {"action": "delay", "task": ".prod.",
                     "delay_s": 0.1}
                ],
            }
        )
        results, errs = [], []

        def run():
            try:
                results.append(client.execute(JOIN_SQL).rows())
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.15)
        rpc.call_json("PUT", ws[0].uri + "/v1/state/drain")
        t.join(120)
        assert not errs, f"drain lost a query: {errs}"
        assert [tuple(r) for r in results[0]] == expected
        # the drained worker's segment entries were materialized or
        # consumed — nothing device-resident pins it alive
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not ws[0]._shutting_down:
            time.sleep(0.05)
        assert ws[0]._shutting_down, "drained worker did not exit"
        # the cluster keeps serving on the survivor
        res = client.execute(
            "select count(*) as c from tpch.tiny.orders"
        )
        assert [tuple(r) for r in res.rows()] == [(15000,)]
    finally:
        _teardown(coord, ws)


def test_cross_slice_worker_keeps_http():
    """A worker announcing a different slice id never rides the
    segment: the scheduler sees mixed slices and keeps the whole
    stage on the wire (correct answers, zero ICI edges)."""
    cfg = {"exchange.ici-enabled": "true"}
    coord = CoordinatorServer(config=NodeConfig(dict(cfg))).start()
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=NodeConfig(dict(cfg))
        ).start(),
        WorkerServer(
            coordinator_uri=coord.uri,
            config=NodeConfig(
                dict(cfg, **{"exchange.slice-id": "other-slice"})
            ),
        ).start(),
    ]
    _wait_workers(coord, 2)
    try:
        e0 = _counter("exchange.ici_edges")
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        expected = [
            tuple(r) for r in coord.local.execute(AGG_SQL).rows()
        ]
        assert [
            tuple(r) for r in client.execute(AGG_SQL).rows()
        ] == expected
        assert _counter("exchange.ici_edges") == e0
    finally:
        _teardown(coord, workers)


# ------------------------------------- per-edge transport (mixed mix)


def test_select_exchange_edges_rules():
    """The per-EDGE successor of the all-or-nothing rule: the DOMINANT
    slice (largest ACTIVE group >= 2) wins; outsiders no longer veto;
    DRAINING workers are excluded but don't demote the rest; nested
    schemas and oversized fan-outs still keep the wire."""
    from presto_tpu import types as T
    from presto_tpu.parallel.exchange import MAX_ICI_PARTS
    from presto_tpu.server.scheduler import select_exchange_edges

    class W:
        def __init__(self, slice_id, state="ACTIVE"):
            self.slice_id = slice_id
            self.state = state

    schema = {"a": T.BIGINT, "b": T.VARCHAR}
    pair = [W("s1"), W("s1")]
    assert select_exchange_edges(pair, True, (schema,)) == "s1"
    # a lone cross-slice worker no longer demotes the stage
    assert (
        select_exchange_edges(pair + [W("s2")], True, (schema,))
        == "s1"
    )
    assert (
        select_exchange_edges(pair + [W("")], True, (schema,)) == "s1"
    )
    # a DRAINING peer is excluded from the count, not a veto
    assert (
        select_exchange_edges(
            pair + [W("s1", state="DRAINING")], True, (schema,)
        )
        == "s1"
    )
    # no pair anywhere -> the wire (a lone worker has no in-slice peer)
    assert select_exchange_edges([W("s1"), W("s2")], True, (schema,)) == ""
    assert select_exchange_edges([W(""), W("")], True, (schema,)) == ""
    # deterministic tie-break: count first, then greatest slice id
    assert (
        select_exchange_edges(
            [W("s1"), W("s1"), W("s2"), W("s2")], True, (schema,)
        )
        == "s2"
    )
    # gate off / nested schema / oversized fan-out keep the wire
    assert select_exchange_edges(pair, False, (schema,)) == ""
    nested = {"a": T.array(T.BIGINT)}
    assert select_exchange_edges(pair, True, (schema, nested)) == ""
    big = [W("s1") for _ in range(MAX_ICI_PARTS + 1)]
    assert select_exchange_edges(big, True, (schema,)) == ""


def test_mixed_transport_stage_per_edge_ici_and_http():
    """The mixed-transport acceptance battery: one cross-slice worker
    in an otherwise co-located cluster. The dominant pair's edges ride
    the segment (ICI edges counted, bytes elided), the outsider's
    edges ride HTTP (http edges counted, wire bytes move), and the
    spliced results are bit-equal to the all-HTTP run."""
    cfg = {"exchange.ici-enabled": "true"}
    coord = CoordinatorServer(config=NodeConfig(dict(cfg))).start()
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=NodeConfig(dict(cfg))
        ).start()
        for _ in range(2)
    ] + [
        WorkerServer(
            coordinator_uri=coord.uri,
            config=NodeConfig(
                dict(cfg, **{"exchange.slice-id": "other-slice"})
            ),
        ).start()
    ]
    _wait_workers(coord, 3)
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        client.execute(
            "set session join_distribution_type = PARTITIONED"
        )
        for sql in (AGG_SQL, JOIN_SQL):
            client.execute(
                "set session exchange_ici_enabled = false"
            )
            rows_http = [tuple(r) for r in client.execute(sql).rows()]

            client.execute("set session exchange_ici_enabled = true")
            e0 = _counter("exchange.ici_edges")
            b0 = _counter("exchange.ici_bytes_elided")
            res = client.execute(sql)
            rows_mixed = [tuple(r) for r in res.rows()]
            assert rows_mixed == rows_http, (
                f"mixed transports changed answers: {sql}"
            )
            # per-edge mix observed end-to-end: the co-located pair's
            # edges rode the segment (zero wire bytes — elided grows),
            # the outsider's edges rode HTTP
            assert _counter("exchange.ici_edges") > e0, sql
            assert _counter("exchange.ici_bytes_elided") > b0, sql
            info = client.query_info(res.query_id)
            assert info["exchange"]["ici_edges"] > 0, sql
            assert info["exchange"]["http_edges"] > 0, sql
    finally:
        _teardown(coord, workers)


def test_collective_trace_failure_falls_open_to_per_source():
    """A collective program that fails to trace must not fail the
    stage: the cache records the failure once, every consumer degrades
    to the PR-14 per-source gather path, and answers are unchanged."""
    import presto_tpu.server.exchange_spi as spi

    coord, ws = _mk_cluster(2, {"exchange.ici-enabled": "true"})
    orig = spi._build_collective
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        client.execute(
            "set session join_distribution_type = PARTITIONED"
        )
        client.execute("set session exchange_ici_enabled = true")
        expected = [
            tuple(r) for r in coord.local.execute(JOIN_SQL).rows()
        ]

        def boom(*a, **k):
            raise RuntimeError("synthetic collective trace failure")

        spi._build_collective = boom
        f0 = _counter("exchange.collective_fallbacks")
        e0 = _counter("exchange.ici_edges")
        rows = [tuple(r) for r in client.execute(JOIN_SQL).rows()]
        assert rows == expected
        assert _counter("exchange.collective_fallbacks") > f0
        # the fallback stays on the ICI lane (per-source gather), not
        # the wire
        assert _counter("exchange.ici_edges") > e0
    finally:
        spi._build_collective = orig
        _teardown(coord, ws)


def test_single_program_collective_stage_and_gather():
    """Single-program mode end-to-end on a co-located cluster: the
    shuffle compiles to ONE collective program per stage
    (exchange.collective_stages counts), and the coordinator's final
    gather rides the ICI lane instead of the serialized HTTP pull."""
    coord, ws = _mk_cluster(2, {"exchange.ici-enabled": "true"})
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        client.execute(
            "set session join_distribution_type = PARTITIONED"
        )
        client.execute("set session exchange_ici_enabled = true")
        expected = [
            tuple(r) for r in coord.local.execute(AGG_SQL).rows()
        ]
        c0 = _counter("exchange.collective_stages")
        res = client.execute(AGG_SQL)
        assert [tuple(r) for r in res.rows()] == expected
        assert _counter("exchange.collective_stages") > c0
        info = client.query_info(res.query_id)
        # merge-task edges + the coordinator's own gather edges all
        # rode ICI; nothing fell back to the wire
        assert info["exchange"]["ici_edges"] > 0
        assert info["exchange"]["http_edges"] == 0
        # single-program off: same answers through the per-source path
        client.execute(
            "set session exchange_single_program = false"
        )
        assert [
            tuple(r) for r in client.execute(AGG_SQL).rows()
        ] == expected
    finally:
        _teardown(coord, ws)


# --------------------------------------- pages_wire floor satellite


def test_compress_floor_skips_probe_and_counts_both_entry_points():
    """Sub-floor buffers ship raw (enc="raw") with no ratio probe, and
    exchange.compress_skipped counts identically whichever producer
    entry point built the frame — device-page serialization and the
    partitioned re-serialize path share the ONE encoder."""
    from presto_tpu import types as T
    from presto_tpu.server import pages_wire

    n = 8  # 64 bytes of int64 — far below the 512B floor
    data = np.arange(n, dtype=np.int64)

    s0 = _counter("exchange.compress_skipped")
    frame_direct = pages_wire.serialize_page(
        [("a", data, None, T.BIGINT, None)], n
    )
    direct_skips = _counter("exchange.compress_skipped") - s0
    assert direct_skips == 1

    # the re-serialize path (partitioned output): payload -> wire
    cols = pages_wire.payload_to_wire_columns(
        {"a": data}, {"a": T.BIGINT}, n
    )
    s1 = _counter("exchange.compress_skipped")
    frame_reser = pages_wire.serialize_page(cols, n)
    assert _counter("exchange.compress_skipped") - s1 == direct_skips
    # both frames mark the buffer raw and decode identically
    for frame in (frame_direct, frame_reser):
        payload, schema, nrows = pages_wire.deserialize_page(frame)
        assert nrows == n
        assert np.array_equal(np.asarray(payload["a"]), data)
    import json as _json
    import struct

    (hlen,) = struct.unpack_from("<I", frame_direct, 4)
    header = _json.loads(frame_direct[8: 8 + hlen].decode())
    assert header["columns"][0]["enc"] == "raw"
