"""Table writer (reference: TableWriterOperator + ConnectorPageSink):
INSERT INTO ... VALUES / SELECT and CREATE TABLE AS over the writable
memory connector; read-only catalogs reject writes."""

import pytest

from presto_tpu.connectors import create_connector
from presto_tpu.exec.local_runner import ExecutionError, LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu import types as T


@pytest.fixture()
def runner():
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    from presto_tpu.connectors.spi import TableHandle

    mem.create_table(
        TableHandle("mem", "default", "kv"),
        {"k": T.INTEGER, "v": T.VARCHAR},
    )
    catalogs.register("mem", mem)
    return LocalQueryRunner(catalogs=catalogs)


def test_insert_values_and_read_back(runner):
    res = runner.execute(
        "insert into mem.default.kv values (1, 'one'), (2, 'two'), "
        "(3, null)"
    )
    assert res.rows() == [(3,)]
    rows = runner.execute(
        "select k, v from mem.default.kv order by k"
    ).rows()
    assert rows == [(1, "one"), (2, "two"), (3, None)]


def test_insert_select(runner):
    res = runner.execute(
        "insert into mem.default.kv "
        "select r_regionkey, r_name from tpch.tiny.region"
    )
    assert res.rows() == [(5,)]
    rows = runner.execute(
        "select count(*) as n from mem.default.kv"
    ).rows()
    assert rows == [(5,)]


def test_create_table_as(runner):
    res = runner.execute(
        "create table mem.default.big_orders as "
        "select o_orderkey, o_totalprice from tpch.tiny.orders "
        "where o_totalprice > 500000"
    )
    n = res.rows()[0][0]
    assert n > 0
    rows = runner.execute(
        "select count(*) as n, min(o_totalprice) as m "
        "from mem.default.big_orders"
    ).rows()
    assert rows[0][0] == n
    assert rows[0][1] > 500000


def test_insert_into_readonly_catalog_fails(runner):
    with pytest.raises(ExecutionError, match="read-only"):
        runner.execute("insert into tpch.tiny.region values (9, 'X', 'c')")


def test_insert_arity_mismatch(runner):
    with pytest.raises(ExecutionError, match="arity"):
        runner.execute("insert into mem.default.kv values (1, 'a', 2)")


def test_insert_invalidates_cached_pages(runner):
    """A write must drop every cached page of the written table: the
    staged-page caches (whole-table and split granularity) otherwise
    serve stale rows to the NEXT query (regression: the second SELECT
    returned the pre-insert count)."""
    runner.execute("insert into mem.default.kv values (1, 'one')")
    assert runner.execute(
        "select count(*) as c from mem.default.kv"
    ).rows() == [(1,)]
    runner.execute("insert into mem.default.kv values (2, 'two')")
    assert runner.execute(
        "select count(*) as c from mem.default.kv"
    ).rows() == [(2,)]


def test_show_columns_and_describe(runner):
    rows = runner.execute("show columns from mem.default.kv").rows()
    assert rows == [("k", "integer"), ("v", "varchar")]
    assert runner.execute("describe mem.default.kv").rows() == rows


def test_delete_where(runner):
    runner.execute(
        "insert into mem.default.kv values (10, 'a'), (11, 'b'), "
        "(12, null)"
    )
    before = runner.execute(
        "select count(*) as c from mem.default.kv"
    ).rows()[0][0]
    # deletes only TRUE rows: the NULL-valued v row stays
    assert runner.execute(
        "delete from mem.default.kv where v = 'b' and k >= 10"
    ).rows() == [(1,)]
    assert runner.execute(
        "select count(*) as c from mem.default.kv"
    ).rows() == [(before - 1,)]
    # unconditional delete empties the table
    runner.execute("delete from mem.default.kv")
    assert runner.execute(
        "select count(*) as c from mem.default.kv"
    ).rows() == [(0,)]


def test_prepare_execute_deallocate(runner):
    runner.execute(
        "insert into mem.default.kv values (1, 'one'), (2, 'two')"
    )
    runner.execute(
        "prepare q from select k, v from mem.default.kv "
        "where k = ? or v = ?"
    )
    assert runner.execute("execute q using 1, 'two'").rows() == [
        (1, "one"),
        (2, "two"),
    ]
    with pytest.raises(ExecutionError, match="2 parameter"):
        runner.execute("execute q using 1")
    runner.execute("deallocate prepare q")
    with pytest.raises(ExecutionError, match="not found"):
        runner.execute("execute q using 1, 'x'")


def test_prepared_insert_and_delete(runner):
    runner.execute(
        "prepare ins2 from insert into mem.default.kv values (?, ?)"
    )
    runner.execute("execute ins2 using 77, 'prep'")
    assert runner.execute(
        "select v from mem.default.kv where k = 77"
    ).rows() == [("prep",)]
    runner.execute(
        "prepare del2 from delete from mem.default.kv where k = ?"
    )
    assert runner.execute("execute del2 using 77").rows() == [(1,)]


def test_create_and_drop_table(runner):
    runner.execute(
        "create table mem.default.ddl (a bigint, s varchar, "
        "d decimal(9,2))"
    )
    assert runner.execute(
        "show columns from mem.default.ddl"
    ).rows() == [
        ("a", "bigint"), ("s", "varchar"), ("d", "decimal(9,2)"),
    ]
    runner.execute(
        "insert into mem.default.ddl values (1, 'x', 2.50)"
    )
    assert runner.execute(
        "select a, s, d from mem.default.ddl"
    ).rows() == [(1, "x", __import__("decimal").Decimal("2.50"))]
    runner.execute("drop table mem.default.ddl")
    with pytest.raises(ExecutionError):
        runner.execute("drop table mem.default.ddl")
    runner.execute("drop table if exists mem.default.ddl")


def test_update(runner):
    runner.execute(
        "create table mem.default.upd (k bigint, v varchar)"
    )
    runner.execute(
        "insert into mem.default.upd values (1, 'a'), (2, 'b'), "
        "(3, null)"
    )
    # NULL predicate rows stay unchanged; count reflects TRUE rows
    assert runner.execute(
        "update mem.default.upd set v = 'z' where v = 'b'"
    ).rows() == [(1,)]
    assert runner.execute(
        "select k, v from mem.default.upd order by k"
    ).rows() == [(1, "a"), (2, "z"), (3, None)]
    # unconditional update touches every row
    assert runner.execute(
        "update mem.default.upd set k = k + 10"
    ).rows() == [(3,)]
    assert runner.execute(
        "select min(k) as m from mem.default.upd"
    ).rows() == [(11,)]
    runner.execute(
        "prepare upd_p from update mem.default.upd set v = ? "
        "where k = ?"
    )
    assert runner.execute("execute upd_p using 'w', 11").rows() == [
        (1,)
    ]
    runner.execute("drop table mem.default.upd")
