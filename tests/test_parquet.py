"""Parquet connector (SURVEY.md §2.2 L9 file-format readers): read
pyarrow-written files through the SPI, with column pruning, row-group
splits, footer statistics, nulls, decimals, dates, and strings."""

import datetime
import decimal

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from presto_tpu.connectors import create_connector  # noqa: E402
from presto_tpu.connectors.spi import TableHandle  # noqa: E402
from presto_tpu.exec.local_runner import LocalQueryRunner  # noqa: E402
from presto_tpu.exec.staging import CatalogManager  # noqa: E402


@pytest.fixture(scope="module")
def lake(tmp_path_factory):
    root = tmp_path_factory.mktemp("lake")
    (root / "sales").mkdir()
    n = 10_000
    rng = np.random.RandomState(7)
    region = rng.choice(["east", "west", "north", None], n, p=[.4, .3, .2, .1])
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "qty": pa.array(rng.randint(1, 100, n).astype(np.int32)),
            "price": pa.array(
                [
                    decimal.Decimal(int(v)) / 100
                    for v in rng.randint(100, 100000, n)
                ],
                type=pa.decimal128(12, 2),
            ),
            "day": pa.array(
                [
                    datetime.date(2024, 1, 1) + datetime.timedelta(days=int(d))
                    for d in rng.randint(0, 365, n)
                ]
            ),
            "region": pa.array(region.tolist()),
            "score": pa.array(rng.rand(n)),
        }
    )
    pq.write_table(
        table, root / "sales" / "orders.parquet", row_group_size=2048
    )
    return root, table


@pytest.fixture(scope="module")
def runner(lake):
    root, _ = lake
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    catalogs.register("lake", create_connector("parquet", root=str(root)))
    return LocalQueryRunner(catalogs=catalogs)


def test_metadata_and_stats(lake):
    root, table = lake
    conn = create_connector("parquet", root=str(root))
    md = conn.metadata()
    assert md.list_schemas() == ["sales"]
    assert md.list_tables("sales") == ["orders"]
    h = TableHandle("lake", "sales", "orders")
    schema = md.get_table_schema(h)
    assert schema["id"].name == "bigint"
    assert schema["price"].is_decimal and schema["price"].scale == 2
    assert schema["region"].is_string
    st = md.get_table_stats(h)
    assert st.row_count == 10_000
    assert st.columns["qty"].min_value >= 1
    assert st.columns["qty"].max_value <= 99


def test_row_group_splits(lake):
    root, _ = lake
    conn = create_connector("parquet", root=str(root))
    h = TableHandle("lake", "sales", "orders")
    src = conn.get_splits(h, target_split_rows=2048)
    splits = []
    while not src.exhausted:
        splits.extend(src.next_batch(16))
    assert len(splits) >= 4
    assert splits[0].row_start == 0
    assert splits[-1].row_end == 10_000


def test_full_scan_agg(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select count(*) as n, sum(qty) as q from lake.sales.orders"
    ).rows()
    assert rows == [(10_000, int(np.sum(table.column("qty").to_numpy())))]


def test_strings_nulls_and_groupby(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select region, count(*) as n from lake.sales.orders "
        "group by region order by region nulls last"
    ).rows()
    regions = table.column("region").to_pylist()
    import collections

    expect = collections.Counter(regions)
    got = {r: n for r, n in rows}
    assert got == dict(expect)


def test_decimal_exactness(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select sum(price) as s from lake.sales.orders where qty < 10"
    ).rows()
    qty = np.asarray(table.column("qty").to_numpy())
    price = [decimal.Decimal(str(v)) for v in table.column("price").to_pylist()]
    expect = sum(p for p, q in zip(price, qty) if q < 10)
    assert rows[0][0] == pytest.approx(float(expect), rel=1e-12)


def test_join_parquet_with_tpch(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select r_name, count(*) as n "
        "from lake.sales.orders, tpch.tiny.region "
        "where qty = r_regionkey group by r_name order by r_name"
    ).rows()
    qty = table.column("qty").to_numpy()
    expect = sum(1 for q in qty if 0 <= q <= 4)
    assert sum(n for _, n in rows) == expect
    assert 0 < len(rows) <= 5


def test_date_filter(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select count(*) as n from lake.sales.orders "
        "where day >= date '2024-07-01'"
    ).rows()
    days = table.column("day").to_pylist()
    expect = sum(1 for d in days if d >= datetime.date(2024, 7, 1))
    assert rows == [(expect,)]
