"""Observability suite: query-lifecycle tracing, the distributed
stats rollup (TaskStats -> StageStats -> QueryStats), the QueryInfo
endpoint ``GET /v1/query/{id}``, /metrics exposition on both node
roles, the query-event JSONL sink, and the metric-name lint.

Reference parity: SURVEY.md §5.1 (QueryStats rollup + QueryInfo),
§5.5 (metrics), and the EventListener SPI.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from presto_tpu.server import CoordinatorServer, PrestoTpuClient, WorkerServer
from presto_tpu.session import NodeConfig
from presto_tpu.utils import tracing
from presto_tpu.utils.metrics import (
    CounterStat,
    DistributionStat,
    MetricsRegistry,

)


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


@pytest.fixture(scope="module")
def event_log(tmp_path_factory):
    return str(tmp_path_factory.mktemp("events") / "events.jsonl")


@pytest.fixture(scope="module")
def cluster(event_log):
    coord = CoordinatorServer(
        config=NodeConfig({"event-listener.path": event_log})
    ).start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    _wait_workers(coord, 2)
    yield coord, workers
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    coord, _ = cluster
    return PrestoTpuClient(coord.uri, timeout_s=600)


@pytest.fixture(scope="module")
def finished_query(client):
    """One distributed query, executed once for the whole module."""
    res = client.execute(
        "select n_regionkey, count(*) c from tpch.tiny.nation "
        "group by n_regionkey"
    )
    assert len(res.rows()) == 5
    return res


# ------------------------------------------------------ tracing primitives


def test_traceparent_roundtrip():
    tid, sid = tracing.new_trace_id(), tracing.new_span_id()
    header = tracing.format_traceparent(tid, sid)
    assert tracing.parse_traceparent(header) == (tid, sid)
    assert tracing.parse_traceparent(None) is None
    assert tracing.parse_traceparent("junk") is None
    assert tracing.parse_traceparent("00-short-short-01") is None


def test_span_tree_nesting_and_cross_thread_parenting():
    tr = tracing.Trace()
    with tr.span("query") as root:
        with tr.span("plan"):
            pass

        def other_thread():
            with tr.span("schedule"):  # no stack here: parents to root
                pass

        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
    tree = tr.to_tree()
    assert len(tree) == 1 and tree[0]["name"] == "query"
    children = {c["name"] for c in tree[0]["children"]}
    assert children == {"plan", "schedule"}
    assert all(s.trace_id == tr.trace_id for s in tr.spans())
    assert tr.traceparent().split("-")[1] == tr.trace_id
    assert root.end > 0


def test_trace_graft_rehomes_foreign_spans():
    tr = tracing.Trace()
    with tr.span("query"):
        pass
    foreign = tracing.synthesize_task_spans(
        trace_id="f" * 32,
        parent_span_id=tr.root.span_id,
        task_id="t1",
        node_id="w1",
        start=time.time() - 1,
        end=time.time(),
        staging_ms=100.0,
        execute_ms=200.0,
    )
    tr.graft(foreign)
    tree = tr.to_tree()
    task = [c for c in tree[0]["children"] if c["name"] == "task"]
    assert len(task) == 1
    assert {c["name"] for c in task[0]["children"]} == {
        "staging", "execute",
    }
    assert all(s.trace_id == tr.trace_id for s in tr.spans())


# -------------------------------------------------------- stats primitives


def test_distribution_quantiles():
    d = DistributionStat()
    for v in range(1, 101):
        d.add(float(v))
    v = d.values()
    assert v["count"] == 100.0
    assert 45 <= v["p50"] <= 56
    assert 85 <= v["p90"] <= 96
    assert 95 <= v["p99"] <= 100
    assert v["min"] == 1.0 and v["max"] == 100.0


def test_distribution_reservoir_is_bounded():
    from presto_tpu.utils.metrics import RESERVOIR_SIZE

    d = DistributionStat()
    for v in range(RESERVOIR_SIZE * 3):
        d.add(float(v))
    assert len(d._reservoir) == RESERVOIR_SIZE
    assert d.count == RESERVOIR_SIZE * 3


def test_stage_stats_rollup():
    from presto_tpu.exec.stats import StageStats, TaskStats

    st = StageStats(stage_id=0)
    st.tasks.append(
        TaskStats(
            task_id="a", query_id="q", wall_ms=10.0,
            input_rows=5, output_rows=2, retries=1,
        )
    )
    st.tasks.append(
        TaskStats(
            task_id="b", query_id="q", wall_ms=30.0,
            input_rows=7, output_rows=3, state="FAILED",
        )
    )
    r = st.rollup()
    assert r["tasks"] == 2
    assert r["wall_ms"] == 30.0  # concurrent tasks: max, not sum
    assert r["input_rows"] == 12
    assert r["output_rows"] == 5
    assert r["retries"] == 1
    assert r["failed_tasks"] == 1
    d = st.to_dict()
    assert d["rollup"]["tasks"] == 2 and len(d["tasks"]) == 2


def test_task_stats_dict_roundtrip():
    from presto_tpu.exec.stats import TaskStats

    t = TaskStats(
        task_id="t", query_id="q", node_id="w", wall_ms=1.5,
        input_rows=10,
    )
    d = t.to_dict()
    d["unknown_future_field"] = 1  # forward-compat: ignored
    t2 = TaskStats.from_dict(d)
    assert t2 == t


# -------------------------------------------------------- metrics registry


def test_prometheus_exposition_has_type_and_help():
    reg = MetricsRegistry()
    reg.counter("obs.test-counter").update(3)
    reg.distribution("obs.lat").add(1.0)
    text = reg.render_prometheus()
    assert "# TYPE presto_tpu_obs_test_counter_total counter" in text
    assert "# HELP presto_tpu_obs_test_counter_total" in text
    assert "presto_tpu_obs_test_counter_total 3.0" in text
    assert "# TYPE presto_tpu_obs_lat summary" in text
    assert 'presto_tpu_obs_lat{quantile="0.5"} 1.0' in text
    assert "presto_tpu_obs_lat_count 1.0" in text


def test_registry_concurrent_updates():
    reg = MetricsRegistry()
    n_threads, n_iter = 8, 500

    def hammer(i):
        for k in range(n_iter):
            reg.counter("conc.counter").update()
            reg.distribution("conc.dist").add(float(k))
            with reg.timer("conc.time").time():
                pass

    threads = [
        threading.Thread(target=hammer, args=(i,))
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("conc.counter").total == n_threads * n_iter
    assert reg.distribution("conc.dist").count == n_threads * n_iter
    assert reg.timer("conc.time").count == n_threads * n_iter
    # rendering under a fresh registration is still well-formed
    assert "# TYPE presto_tpu_conc_counter_total counter" in (
        reg.render_prometheus()
    )


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).


# --------------------------------------------------------- HTTP endpoints


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, resp.read().decode()


def test_metrics_endpoint_coordinator(cluster, finished_query):
    coord, _ = cluster
    status, text = _get(coord.uri + "/v1/metrics")
    assert status == 200
    assert "# TYPE presto_tpu_coordinator_query_time summary" in text
    assert "# HELP presto_tpu_coordinator_query_time" in text
    # compile-amortization + staging metrics recorded by the engine
    # (worker.staging_bytes: the split-staging path every distributed
    # scan takes; staging.bytes covers whole-table local loads)
    assert "presto_tpu_compile_cache_miss_total" in text
    assert "presto_tpu_worker_staging_bytes" in text


def test_metrics_endpoint_worker(cluster, finished_query):
    _, workers = cluster
    status, text = _get(workers[0].uri + "/v1/metrics")
    assert status == 200
    assert "presto_tpu_worker_tasks_created_total" in text
    assert "# TYPE presto_tpu_worker_task_time summary" in text


def test_query_info_endpoint(client, finished_query):
    info = client.query_info(finished_query.query_id)
    assert info["state"] == "FINISHED"
    assert info["query_id"] == finished_query.query_id
    assert len(info["trace_id"]) == 32
    # per-stage StageStats with task-level timings
    assert info["stages"], "distributed query must produce stages"
    stage = info["stages"][0]
    assert stage["rollup"]["tasks"] >= 1
    assert stage["rollup"]["input_rows"] == 25  # nation scanned in full
    task = stage["tasks"][0]
    assert task["state"] == "FINISHED"
    assert task["wall_ms"] > 0
    assert task["node_id"].startswith("worker-")
    assert task["output_rows"] >= 1
    # the span tree covers the lifecycle phases with ONE trace id
    def walk(nodes):
        for n in nodes:
            yield n
            yield from walk(n["children"])

    spans = list(walk(info["trace"]))
    names = {s["name"] for s in spans}
    assert {"query", "plan", "schedule", "task", "gather"} <= names
    assert {s["trace_id"] for s in spans} == {info["trace_id"]}
    # worker-side task spans carry the originating node
    task_spans = [s for s in spans if s["name"] == "task"]
    assert all(
        s["attrs"]["node_id"].startswith("worker-") for s in task_spans
    )


def test_query_listing_endpoint(client, finished_query):
    listing = client.list_queries()
    mine = [
        s for s in listing if s["query_id"] == finished_query.query_id
    ]
    assert len(mine) == 1
    assert mine[0]["state"] == "FINISHED"
    assert mine[0]["trace_id"]


def test_query_info_404(cluster):
    coord, _ = cluster
    req = urllib.request.Request(coord.uri + "/v1/query/nope")
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_query_history_eviction(monkeypatch):
    """Completed queries age out of the coordinator's query map beyond
    MAX_QUERY_HISTORY; running/queued ones are never evicted. Own
    coordinator: eviction must not touch the shared cluster fixture."""
    from presto_tpu.server import coordinator as coord_mod

    monkeypatch.setattr(coord_mod, "MAX_QUERY_HISTORY", 2)
    coord = CoordinatorServer()
    try:
        done_ids = []
        for i in range(4):
            q = coord_mod._Query(f"q_evict{i}", "select 1")
            q.state = "FINISHED"
            q._drained = True  # results fully served: evictable
            q.done.set()
            with coord._lock:
                coord.queries[q.qid] = q
            done_ids.append(q.qid)
        undrained = coord_mod._Query("q_evict_undrained", "select 1")
        undrained.state = "FINISHED"
        undrained.stats.end_time = time.time()
        undrained.done.set()  # done but client still paginating
        running = coord_mod._Query("q_evict_run", "select 1")
        running.state = "RUNNING"
        with coord._lock:
            coord.queries[undrained.qid] = undrained
            coord.queries[running.qid] = running
        q = coord.submit("set session tpu_offload = true")
        assert q.done.wait(30)
        with coord._lock:
            kept = set(coord.queries)
        assert "q_evict_run" in kept  # running: never evicted
        # done-but-undrained inside the grace window: protected
        assert "q_evict_undrained" in kept
        # the oldest drained completed queries beyond the cap are gone
        assert done_ids[0] not in kept and done_ids[1] not in kept
    finally:
        coord.shutdown()


def test_system_runtime_tasks(client, finished_query):
    rows = client.execute(
        "select query_id, stage_id, task_id, node_id, state, wall_ms "
        "from system.runtime.tasks where query_id = "
        f"'{finished_query.query_id}'"
    ).rows()
    assert rows, "runtime.tasks must list the finished query's tasks"
    assert all(r[4] == "FINISHED" for r in rows)
    assert all(r[5] > 0 for r in rows)


def test_system_runtime_queries_sees_distributed(client, finished_query):
    rows = client.execute(
        "select query_id, state, trace_id from system.runtime.queries "
        f"where query_id = '{finished_query.query_id}'"
    ).rows()
    assert len(rows) == 1
    assert rows[0][1] == "FINISHED"
    assert len(rows[0][2]) == 32


def test_distributed_explain_analyze(client):
    res = client.execute(
        "explain analyze select count(*) c from tpch.tiny.region"
    )
    text = "\n".join(r[0] for r in res.rows())
    assert "Distributed EXPLAIN ANALYZE" in text
    assert "Stage 0 [source]" in text
    assert "Task " in text
    assert "Span tree:" in text
    assert "- schedule" in text
    assert "trace " in text


def test_query_event_jsonl_sink(client, event_log, finished_query):
    client.execute("select count(*) c from tpch.tiny.region")
    deadline = time.time() + 5
    events = []
    while time.time() < deadline:
        if os.path.exists(event_log):
            with open(event_log) as f:
                events = [json.loads(line) for line in f]
            if len(events) >= 2:
                break
        time.sleep(0.1)
    assert events, "event sink must receive query_completed records"
    ev = events[-1]
    assert ev["event"] == "query_completed"
    assert ev["state"] == "FINISHED"
    assert len(ev["trace_id"]) == 32
    assert "stages" in ev and "spans" in ev
    span_names = {s["name"] for s in ev["spans"]}
    assert "query" in span_names


def test_worker_status_carries_task_stats(cluster):
    """POST a task directly with a traceparent header: the status
    response must carry TaskStats and trace-joined spans."""
    from presto_tpu.plan import nodes as N
    from presto_tpu.connectors.spi import TableHandle
    from presto_tpu.server.protocol import FragmentSpec

    _, workers = cluster
    w = workers[0]
    handle = TableHandle("tpch", "tiny", "region")
    schema = w.runner.catalogs.get("tpch").metadata().get_table_schema(
        handle
    )
    scan = N.TableScanNode(
        handle=handle,
        columns=("r_regionkey",),
        schema=(("r_regionkey", schema["r_regionkey"]),),
    )
    trace_id, span_id = tracing.new_trace_id(), tracing.new_span_id()
    spec = FragmentSpec(
        task_id="obs-test-task",
        query_id="obs-test",
        fragment=scan,
        partition_scan=0,
        split_start=0,
        split_end=5,
        traceparent=tracing.format_traceparent(trace_id, span_id),
    )
    body = json.dumps(spec.to_json()).encode()
    req = urllib.request.Request(
        w.uri + "/v1/task", data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    urllib.request.urlopen(req, timeout=30).read()
    deadline = time.time() + 120  # generous: cold compile under load
    st = {}
    while time.time() < deadline:
        _, raw = _get(w.uri + "/v1/task/obs-test-task/status")
        st = json.loads(raw)
        if st["state"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.05)
    assert st["state"] == "FINISHED", st.get("error")
    assert st["stats"]["input_rows"] == 5
    assert st["stats"]["output_rows"] == 5
    assert st["stats"]["wall_ms"] > 0
    span_ids = {s["trace_id"] for s in st["spans"]}
    assert span_ids == {trace_id}  # worker honored the propagated trace
    parents = {s["parent_id"] for s in st["spans"]}
    assert span_id in parents  # task span hangs off the coordinator span
    req = urllib.request.Request(
        w.uri + "/v1/task/obs-test-task", method="DELETE"
    )
    urllib.request.urlopen(req, timeout=30).read()
