"""Stage-at-a-time device execution (reference: tasks execute plan
*fragments*, never whole plans — SURVEY.md §3.3). A tight
``max_fragment_weight`` forces every TPC-H query through the
fragment-at-a-time executor (heavy subtrees compile as their own XLA
programs, intermediates stay device-resident) and the results must be
oracle-exact — identical to whole-plan execution."""

import pytest

from presto_tpu.exec.local_runner import (
    LocalQueryRunner,
    _plan_weight,
)
from presto_tpu.session import Session
from presto_tpu.verifier import SqliteOracle, verify_query

from tpch_queries import QUERIES


@pytest.fixture(scope="module")
def runner():
    # weight 8 fragments everything with >1 heavy node: joins,
    # aggregations, sorts each weigh 6
    return LocalQueryRunner(
        session=Session(properties={"max_fragment_weight": 8})
    )


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query_fragmented(qnum, runner, oracle):
    diff = verify_query(runner, oracle, QUERIES[qnum], rel_tol=1e-6)
    assert diff is None, f"Q{qnum} mismatch (fragmented): {diff}"


def test_fragment_count_reported(runner):
    """A multi-join query under a tight budget must actually execute
    multiple device programs (device_fragments > 0) — i.e. the
    fragmented path ran, not the whole-plan path."""
    runner.execute(QUERIES[3])
    qs = runner.history.snapshot()[-1]
    assert qs.device_fragments > 0, qs


def test_small_plans_stay_whole():
    """Q1-class plans under the default budget compile as ONE program
    (no extra round trips on the fast path)."""
    r = LocalQueryRunner()
    r.execute(QUERIES[1])
    qs = r.history.snapshot()[-1]
    assert qs.device_fragments == 0, qs


def test_weight_counts_heavy_nodes():
    from presto_tpu.plan.planner import plan_statement
    from presto_tpu.sql import parse_statement

    r = LocalQueryRunner()
    plan = plan_statement(
        parse_statement(QUERIES[5]), r.catalogs, r.session
    )
    w = _plan_weight(plan.root)
    assert w > 28, w  # Q5 (6-table join) must exceed the default budget


def test_dynamic_filtering_applies(runner, oracle):
    """Build-first fragment execution feeds runtime build-key ranges
    into probe-side filters (reference: dynamic filtering, SURVEY.md
    §3.2) — filters fire AND the result stays oracle-exact."""
    diff = verify_query(runner, oracle, QUERIES[10], rel_tol=1e-6)
    assert diff is None, diff
    qs = runner.history.snapshot()[-1]
    assert qs.dynamic_filters > 0, qs


def test_dynamic_filtering_can_disable(oracle):
    from presto_tpu.session import Session

    r = LocalQueryRunner(
        session=Session(
            properties={
                "max_fragment_weight": 8,
                "enable_dynamic_filtering": "false",
            }
        )
    )
    diff = verify_query(r, oracle, QUERIES[10], rel_tol=1e-6)
    assert diff is None, diff
    qs = r.history.snapshot()[-1]
    assert qs.dynamic_filters == 0, qs
