"""C++ host-agent codec (native/dict_codec.cpp via ctypes): parity with
the numpy dictionary-encode path, including nulls, duplicates, unicode,
and empty strings; gated gracefully when the toolchain is absent."""

import numpy as np
import pytest

from presto_tpu import native
from presto_tpu.page import Dictionary, encode_strings

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _check_parity(values):
    arr = np.asarray(values, dtype=object)
    ids_n, valid_n, uniq_n = native.encode_strings_native(arr)
    ids_p, valid_p, dic_p = encode_strings(arr, force_numpy=True)
    assert (valid_n == valid_p).all()
    assert (ids_n[valid_n] == ids_p[valid_p]).all()
    assert list(uniq_n) == list(dic_p.values)


def test_parity_basic():
    _check_parity(["b", "a", "c", "a", None, "b", ""])


def test_parity_unicode_and_dupes():
    _check_parity(["héllo", "wörld", "héllo", "zebra", "äpfel"] * 7)


def test_parity_all_null():
    _check_parity([None, None, None])


def test_parity_single():
    _check_parity(["only"])


def test_engine_route_uses_native_above_threshold():
    """encode_strings transparently routes large columns natively and
    produces an order-preserving Dictionary either way."""
    rng = np.random.RandomState(3)
    pool = [f"w{i:05d}" for i in range(200)]
    vals = np.asarray(
        [pool[i] for i in rng.randint(0, 200, 10_000)], dtype=object
    )
    ids, valid, dic = encode_strings(vals)
    assert isinstance(dic, Dictionary)
    decoded = dic.values[ids]
    assert (decoded == vals).all()
    # order-preserving: id comparison == lexicographic comparison
    order = np.argsort(ids[:100], kind="stable")
    strs = [str(v) for v in vals[:100][order]]
    assert strs == sorted(strs)


# ------------------------------------------------ closed-form generator


def test_gen_uniform_parity():
    """native/genstream.cpp must match the numpy closed form bit for
    bit — both the engine and the sqlite oracle generate data through
    _uniform, so any divergence would poison every oracle diff."""
    from presto_tpu import native
    from presto_tpu.connectors.tpch import _stream

    if native._load_gen() is None:
        pytest.skip("native toolchain unavailable")
    n = native._GEN_MIN_ROWS + 3
    for tag, start, step, lo, hi in [
        (1701, 0, 1, 1, 200_000),
        (1702, 12_345, 1, -5000, 5000),
        (1801, 0, 2, 100, 10_000),
        (2201, 7, 3, 1, 1),
    ]:
        idx = start + step * np.arange(n, dtype=np.int64)
        got = native.gen_uniform_native(tag, idx, lo, hi)
        assert got is not None
        span = (_stream(tag, idx) % np.uint64(hi - lo + 1)).astype(
            np.int64
        )
        np.testing.assert_array_equal(got, lo + span)


def test_gen_uniform_rejects_non_affine():
    from presto_tpu import native

    if native._load_gen() is None:
        pytest.skip("native toolchain unavailable")
    idx = np.arange(native._GEN_MIN_ROWS + 5, dtype=np.int64)
    idx[17] += 1  # not affine
    assert native.gen_uniform_native(1701, idx, 0, 10) is None


def test_generator_route_matches_numpy_end_to_end():
    """A table slice generated with the native route must equal the
    pure-numpy result (force-disable, regenerate, compare)."""
    from presto_tpu import native
    from presto_tpu.connectors.tpch import TpchGenerator

    if native._load_gen() is None:
        pytest.skip("native toolchain unavailable")
    n = native._GEN_MIN_ROWS + 10
    g = TpchGenerator(1.0)
    cols = ["l_orderkey", "l_quantity", "l_extendedprice", "l_shipdate"]
    with_native = g.generate("lineitem", 0, n, cols)
    saved = native._gen_lib
    try:
        native._gen_lib = None
        without = g.generate("lineitem", 0, n, cols)
    finally:
        native._gen_lib = saved
    for c in cols:
        np.testing.assert_array_equal(
            np.asarray(with_native[c]), np.asarray(without[c]), err_msg=c
        )
