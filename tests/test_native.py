"""C++ host-agent codec (native/dict_codec.cpp via ctypes): parity with
the numpy dictionary-encode path, including nulls, duplicates, unicode,
and empty strings; gated gracefully when the toolchain is absent."""

import numpy as np
import pytest

from presto_tpu import native
from presto_tpu.page import Dictionary, encode_strings

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _check_parity(values):
    arr = np.asarray(values, dtype=object)
    ids_n, valid_n, uniq_n = native.encode_strings_native(arr)
    ids_p, valid_p, dic_p = encode_strings(arr, force_numpy=True)
    assert (valid_n == valid_p).all()
    assert (ids_n[valid_n] == ids_p[valid_p]).all()
    assert list(uniq_n) == list(dic_p.values)


def test_parity_basic():
    _check_parity(["b", "a", "c", "a", None, "b", ""])


def test_parity_unicode_and_dupes():
    _check_parity(["héllo", "wörld", "héllo", "zebra", "äpfel"] * 7)


def test_parity_all_null():
    _check_parity([None, None, None])


def test_parity_single():
    _check_parity(["only"])


def test_engine_route_uses_native_above_threshold():
    """encode_strings transparently routes large columns natively and
    produces an order-preserving Dictionary either way."""
    rng = np.random.RandomState(3)
    pool = [f"w{i:05d}" for i in range(200)]
    vals = np.asarray(
        [pool[i] for i in rng.randint(0, 200, 10_000)], dtype=object
    )
    ids, valid, dic = encode_strings(vals)
    assert isinstance(dic, Dictionary)
    decoded = dic.values[ids]
    assert (decoded == vals).all()
    # order-preserving: id comparison == lexicographic comparison
    order = np.argsort(ids[:100], kind="stable")
    strs = [str(v) for v in vals[:100][order]]
    assert strs == sorted(strs)
