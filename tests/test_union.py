"""Set operations: UNION [ALL] (reference: UnionNode — SURVEY.md §2.1
"Logical planner"). Left-associative chains, positional column
alignment with type coercion, cross-dictionary string re-encoding,
unions as FROM subqueries, ORDER BY/LIMIT over the whole chain —
everything diffed against the sqlite oracle."""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


QUERIES = {
    "all_strings_cross_dict": (
        "select n_name as x from tpch.tiny.nation where n_nationkey < 3 "
        "union all select r_name from tpch.tiny.region order by x"
    ),
    "distinct_dedups": (
        "select n_regionkey as k from tpch.tiny.nation "
        "union select r_regionkey from tpch.tiny.region order by k"
    ),
    "in_from_subquery": (
        "select count(*) as c from (select n_nationkey as k "
        "from tpch.tiny.nation union all "
        "select r_regionkey from tpch.tiny.region) t"
    ),
    "mixed_all_then_distinct": (
        "select n_nationkey as k from tpch.tiny.nation "
        "where n_nationkey < 2 "
        "union all select n_nationkey from tpch.tiny.nation "
        "where n_nationkey < 2 "
        "union select 99 order by k"
    ),
    "numeric_coercion": (
        "select sum(v) as s from (select o_totalprice as v "
        "from tpch.tiny.orders union all "
        "select l_extendedprice from tpch.tiny.lineitem) u"
    ),
    "group_over_union": (
        "select k, count(*) as c from (select n_regionkey as k "
        "from tpch.tiny.nation union all "
        "select r_regionkey from tpch.tiny.region) t "
        "group by k order by k"
    ),
    "union_with_limit": (
        "select n_nationkey as k from tpch.tiny.nation union all "
        "select r_regionkey from tpch.tiny.region "
        "order by k desc limit 7"
    ),
    "parenthesized_terms": (
        "(select n_nationkey as k from tpch.tiny.nation "
        "where n_nationkey < 3) union all "
        "(select r_regionkey from tpch.tiny.region "
        "where r_regionkey > 2) order by k"
    ),
    "joined_channels": (
        "select src, sum(rev) as total from ("
        "  select 1 as src, o_totalprice as rev from tpch.tiny.orders "
        "  where o_orderpriority = '1-URGENT'"
        "  union all "
        "  select 2 as src, l_extendedprice from tpch.tiny.lineitem "
        "  where l_shipmode = 'AIR') ch "
        "group by src order by src"
    ),
}


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_union(name, runner, oracle):
    diff = verify_query(runner, oracle, QUERIES[name], rel_tol=1e-6)
    assert diff is None, f"{name}: {diff}"


def test_union_arity_mismatch(runner):
    from presto_tpu.plan.planner import PlanningError

    with pytest.raises(PlanningError):
        runner.execute(
            "select n_nationkey, n_name from tpch.tiny.nation "
            "union all select r_regionkey from tpch.tiny.region"
        )


def test_parenthesized_statement_keeps_order_limit(runner, oracle):
    """A top-level parenthesized query must keep its INNER order/limit
    (a blanket replace once wiped them silently)."""
    q = (
        "(select n_name from tpch.tiny.nation "
        "order by n_name desc limit 3)"
    )
    rows = runner.execute(q).rows()
    assert len(rows) == 3
    assert rows == sorted(rows, reverse=True)
    diff = verify_query(runner, oracle, q)
    assert diff is None, diff


def test_correlated_exists_over_union_raises_cleanly(runner):
    """EXISTS over a correlated UNION is outside the conjunct-level
    decorrelation machinery: it must fail with a loud PlanningError
    (never wrong answers). Uncorrelated unions inside IN/EXISTS work."""
    from presto_tpu.plan.planner import PlanningError

    q = (
        "select n_name from tpch.tiny.nation n where exists ("
        "select r_regionkey as k from tpch.tiny.region "
        "where r_regionkey = n.n_regionkey "
        "union all select r_regionkey from tpch.tiny.region "
        "where r_regionkey = n.n_regionkey) "
        "order by n_name limit 5"
    )
    with pytest.raises(PlanningError):
        runner.execute(q)


def test_uncorrelated_union_in_subquery_predicate(runner, oracle):
    q = (
        "select count(*) as c from tpch.tiny.nation "
        "where n_regionkey in (select r_regionkey from "
        "tpch.tiny.region where r_regionkey < 2 "
        "union all select 4)"
    )
    diff = verify_query(runner, oracle, q)
    assert diff is None, diff


# --------------------------------------------------- INTERSECT / EXCEPT


INTERSECT_QUERIES = {
    "intersect_basic": (
        "select n_regionkey as k from tpch.tiny.nation "
        "intersect select r_regionkey from tpch.tiny.region "
        "where r_regionkey < 3 order by k"
    ),
    "except_basic": (
        "select n_nationkey as k from tpch.tiny.nation "
        "where n_nationkey < 8 "
        "except select r_regionkey from tpch.tiny.region order by k"
    ),
    "intersect_strings": (
        "select n_name as x from tpch.tiny.nation "
        "intersect select n_name from tpch.tiny.nation "
        "where n_regionkey = 2 order by x"
    ),
    "precedence_intersect_binds_tighter": (
        "select n_nationkey as k from tpch.tiny.nation "
        "where n_nationkey < 3 "
        "union select n_nationkey from tpch.tiny.nation "
        "where n_nationkey between 3 and 6 "
        "intersect select n_nationkey from tpch.tiny.nation "
        "where n_nationkey between 5 and 9 order by k"
    ),
    "except_dedups": (
        "select n_regionkey as k from tpch.tiny.nation "
        "except select 99 order by k"
    ),
}


@pytest.mark.parametrize("name", sorted(INTERSECT_QUERIES))
def test_intersect_except(name, runner, oracle):
    diff = verify_query(
        runner, oracle, INTERSECT_QUERIES[name], rel_tol=1e-6
    )
    assert diff is None, f"{name}: {diff}"


# ------------------------------------------------------ VALUES relation


VALUES_QUERIES = {
    "basic_with_null": (
        "select a, b from (values (1, 'x'), (2, 'y'), (3, null)) "
        "as t(a, b) order by a"
    ),
    "expression_over_values": (
        "select t.a + 1 as a1 from (values (1), (2)) t(a) order by a1"
    ),
    "joined_to_table": (
        "select n_name from tpch.tiny.nation, (values (1), (2)) v(k) "
        "where n_nationkey = v.k order by n_name"
    ),
    "mixed_numeric_literals": (
        "select sum(a) as s from (values (1.5), (2), (3.25)) t(a)"
    ),
    "default_column_names": (
        "select count(*) as c from (values (1, 2), (3, 4)) t"
    ),
}


@pytest.mark.parametrize("name", sorted(VALUES_QUERIES))
def test_values_relation(name, runner, oracle):
    diff = verify_query(runner, oracle, VALUES_QUERIES[name], rel_tol=1e-6)
    assert diff is None, f"{name}: {diff}"


def test_values_arity_mismatch(runner):
    from presto_tpu.plan.planner import PlanningError

    with pytest.raises(PlanningError):
        runner.execute("select * from (values (1, 2), (3)) t(a, b)")
