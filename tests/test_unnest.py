"""ARRAY[...] expressions + UNNEST (SURVEY.md §2.1 "Operators":
UnnestOperator parity). Arrays are trace-time expression lists, so
UNNEST is a static-width row expansion and the array scalar functions
fold into ordinary expressions — every shape stays static for XLA."""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def test_unnest_constants_standalone(runner):
    rows = runner.execute(
        "select x from unnest(array[3, 1, 2]) as t(x) order by x"
    ).rows()
    assert rows == [(1,), (2,), (3,)]


def test_unnest_with_ordinality(runner):
    rows = runner.execute(
        "select x, n from unnest(array[30, 10, 20]) "
        "with ordinality as t(x, n) order by n"
    ).rows()
    assert rows == [(30, 1), (10, 2), (20, 3)]


def test_unnest_lateral_columns(runner):
    """Elements referencing the left relation's columns (lateral)."""
    rows = runner.execute(
        "select r_regionkey, v from tpch.tiny.region "
        "cross join unnest(array[r_regionkey, r_regionkey * 10]) as u(v) "
        "order by r_regionkey, v"
    ).rows()
    expect = []
    for k in range(5):
        expect += [(k, k), (k, k * 10)] if k else [(0, 0), (0, 0)]
    assert rows == sorted(expect)


def test_unnest_aggregation_over_expanded_rows(runner):
    """The expansion multiplies row counts exactly (5 regions x 3)."""
    rows = runner.execute(
        "select count(*) as n, sum(v) as s from tpch.tiny.region "
        "cross join unnest(array[1, 2, 3]) as u(v)"
    ).rows()
    assert rows == [(15, 5 * 6)]


def test_unnest_filter_on_element(runner):
    rows = runner.execute(
        "select r_name, v from tpch.tiny.region "
        "cross join unnest(array[r_regionkey, 7]) as u(v) "
        "where v > 3 order by r_name, v"
    ).rows()
    names = [
        r[0]
        for r in runner.execute(
            "select r_name from tpch.tiny.region order by r_name"
        ).rows()
    ]
    expect = sorted(
        [(n, 7) for n in names]
        + [(n, 4) for n in names if n == "MIDDLE EAST"]
    )
    assert rows == expect


def test_unnest_string_elements_mixed_dictionaries(runner):
    """String elements from different dictionaries (a column and a
    literal) must land in one coherent output dictionary."""
    rows = runner.execute(
        "select r_regionkey, s from tpch.tiny.region "
        "cross join unnest(array[r_name, 'zzz']) as u(s) "
        "order by r_regionkey, s"
    ).rows()
    names = dict(
        runner.execute(
            "select r_regionkey, r_name from tpch.tiny.region"
        ).rows()
    )
    expect = sorted(
        [(k, names[k]) for k in names] + [(k, "zzz") for k in names]
    )
    assert rows == expect


def test_unnest_nulls_pass_through(runner):
    rows = runner.execute(
        "select v from unnest(array[1, null, 3]) as t(v) "
        "order by v nulls last"
    ).rows()
    assert rows == [(1,), (3,), (None,)]


def test_cardinality(runner):
    rows = runner.execute(
        "select cardinality(array[1, 2, 3]) as c"
    ).rows()
    assert rows == [(3,)]


def test_element_at_literal_index(runner):
    rows = runner.execute(
        "select element_at(array[10, 20, 30], 2) as e"
    ).rows()
    assert rows == [(20,)]


def test_element_at_out_of_range_is_null(runner):
    rows = runner.execute(
        "select element_at(array[10, 20], 5) as e"
    ).rows()
    assert rows == [(None,)]


def test_subscript_sugar(runner):
    rows = runner.execute(
        "select array[10, 20, 30][2] as e"
    ).rows()
    assert rows == [(20,)]


def test_element_at_column_index(runner):
    """Non-literal index lowers to a CASE chain."""
    rows = runner.execute(
        "select r_regionkey, "
        "element_at(array[100, 200], r_regionkey) as e "
        "from tpch.tiny.region order by r_regionkey"
    ).rows()
    assert rows == [
        (0, None), (1, 100), (2, 200), (3, None), (4, None),
    ]


def test_contains(runner):
    rows = runner.execute(
        "select r_name from tpch.tiny.region "
        "where contains(array[0, 2], r_regionkey) "
        "order by r_name"
    ).rows()
    names = dict(
        runner.execute(
            "select r_regionkey, r_name from tpch.tiny.region"
        ).rows()
    )
    assert rows == sorted([(names[0],), (names[2],)])


def test_unnest_explain_shows_node(runner):
    txt = "\n".join(
        r[0]
        for r in runner.execute(
            "explain select v from unnest(array[1, 2]) as t(v)"
        ).rows()
    )
    assert "Unnest[v x2]" in txt


def test_unnest_join_then_unnest(runner):
    """Unnest composed with a real join (explicit JOIN ... ON)."""
    rows = runner.execute(
        "select n_name, v from tpch.tiny.nation "
        "join tpch.tiny.region on n_regionkey = r_regionkey "
        "cross join unnest(array[r_regionkey]) as u(v) "
        "where n_name = 'CANADA'"
    ).rows()
    assert rows == [("CANADA", 1)]
