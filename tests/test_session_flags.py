"""Session-property wiring tests: every property must be observable in
engine behavior (VERDICT round-1: no decorative flags). Reference:
SystemSessionProperties, SURVEY.md §5.6."""

import time

import jax
import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.parallel import DistributedQueryRunner
from presto_tpu.session import Session
from presto_tpu.verifier import SqliteOracle, verify_offload, verify_query

Q_AGG = (
    "select l_returnflag, count(*) as c, sum(l_quantity) as s "
    "from tpch.tiny.lineitem group by l_returnflag order by l_returnflag"
)

Q_JOIN = (
    "select o_orderpriority, count(*) as c from tpch.tiny.orders, "
    "tpch.tiny.customer where o_custkey = c_custkey "
    "group by o_orderpriority order by o_orderpriority"
)


def test_tpu_offload_changes_execution_device():
    """tpu_offload=false pins staging + execution to the first CPU
    device (the BASELINE.json dual-backend session gate)."""
    cpu0 = jax.devices("cpu")[0]
    off = LocalQueryRunner(
        session=Session(properties={"tpu_offload": False})
    )
    res = off.execute(Q_AGG)
    page = res.page
    assert all(
        b.data.devices() == {cpu0} for b in page.blocks
    ), "offload-off result must live on the first CPU device"
    # flag flip mid-session recompiles rather than reusing the cache
    on = LocalQueryRunner(session=Session(properties={"tpu_offload": True}))
    res2 = on.execute(Q_AGG)
    assert [tuple(r) for r in res.rows()] == [
        tuple(r) for r in res2.rows()
    ]


def test_verify_offload_mode():
    assert verify_offload(Q_AGG) is None
    assert verify_offload(Q_JOIN) is None


def test_join_distribution_type_forced_modes(oracle_mod):
    """PARTITIONED and BROADCAST forced modes both produce oracle-exact
    results (AUTOMATIC is covered by the main distributed suite)."""
    for mode in ("PARTITIONED", "BROADCAST"):
        r = DistributedQueryRunner(
            session=Session(properties={"join_distribution_type": mode}),
            broadcast_threshold=1 << 11,
            repl_threshold=1 << 10,
        )
        diff = verify_query(r, oracle_mod, Q_JOIN)
        assert diff is None, f"{mode}: {diff}"


def test_hash_partition_count_sets_mesh_width():
    r = DistributedQueryRunner(
        session=Session(properties={"hash_partition_count": 4})
    )
    assert r.n == 4
    r8 = DistributedQueryRunner()
    assert r8.n == len(jax.devices())


def test_task_concurrency_and_split_batches_over_http(oracle_mod):
    """Small split batches + concurrent drivers stream many partial
    pages per task; results stay oracle-exact."""
    from presto_tpu.server import (
        CoordinatorServer,
        PrestoTpuClient,
        WorkerServer,
    )

    coord = CoordinatorServer().start()
    coord.local.session.set("page_capacity", 1 << 12)  # 4096-row batches
    coord.local.session.set("task_concurrency", 2)
    w = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not coord.active_workers():
            time.sleep(0.05)
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        diff = verify_query(client, oracle_mod, Q_AGG)
        assert diff is None, diff
    finally:
        w.shutdown(graceful=False)
        coord.shutdown()


@pytest.fixture(scope="module")
def oracle_mod():
    return SqliteOracle("tiny")


def test_speculative_result_rows_single_round_trip(oracle_mod):
    """speculative_result_rows pins the one-round-trip materialization:
    a small aggregate result must need exactly ONE device_get; with the
    property 0, the control+materialize pair (two fetches) returns."""
    import jax

    from presto_tpu.exec import local_runner as LR

    r = LR.LocalQueryRunner()
    sql = (
        "select l_returnflag, count(*) as n from tpch.tiny.lineitem "
        "group by l_returnflag order by l_returnflag"
    )
    r.execute(sql).rows()  # warm: staging + compile out of the count

    calls = []
    orig = jax.device_get

    def spy(x):
        calls.append(1)
        return orig(x)

    jax.device_get, LR.jax.device_get = spy, spy
    try:
        rows1 = r.execute(sql).rows()
        one = len(calls)
        calls.clear()
        r.session.set("speculative_result_rows", 0)
        rows2 = r.execute(sql).rows()
        two = len(calls)
    finally:
        jax.device_get = LR.jax.device_get = orig
        r.session.set("speculative_result_rows", 1024)
    assert rows1 == rows2
    diff = verify_query(r, oracle_mod, sql)
    assert diff is None, diff
    assert one == 1, f"speculative path used {one} fetches"
    assert two == 2, f"fallback path used {two} fetches"
