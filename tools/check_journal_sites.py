#!/usr/bin/env python
"""Journal-site lint: coordinator-journal frame construction and replay
must be confined to ``presto_tpu/server/journal.py`` (the one audited
module), with ``server/coordinator.py`` as the audited CONSUMER of its
record/replay API (and ``server/memory_arbiter.py`` for kill frames).

Shim over the unified AST framework (``tools/analysis``, rule
``journal-sites``) — exits 0 when clean, 1 with a report. Run every
pass at once with ``tools/analyze.py``; wired into the test suite via
tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "journal-sites"


def scan(src_dir):
    """(path, line, kind, source-line) for every journal site outside
    its audited module(s)."""
    out = []
    for f in legacy.shim_findings(RULE, src_dir):
        kind = (
            "frame"
            if ("frame internal" in f.message or "segment-name" in f.message)
            else "consumer"
        )
        out.append((f.path, f.line, kind, f.snippet))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_journal_sites: journal frames confined to "
            "server/journal.py (consumer: server/coordinator.py)"
        )
        return 0
    for path, lineno, kind, line in sites:
        print(f"AD-HOC JOURNAL SITE ({kind}): {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc journal site(s) — route them through "
        "presto_tpu.server.journal instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
