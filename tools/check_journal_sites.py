#!/usr/bin/env python
"""Journal-site lint: coordinator-journal frame construction and replay
must be confined to ``presto_tpu/server/journal.py`` (the one audited
module), with ``server/coordinator.py`` as the one audited CONSUMER of
its record/replay API.

Coordinator HA hangs on the journal's replayability: a restarted
coordinator re-admits exactly the queries whose submit frame has no
finish frame. An ad-hoc frame writer elsewhere (hand-rolled crc line, a
segment file opened under the journal directory, a duplicate replay
loop) would silently fork that truth — resumed-twice queries or
forgotten ones, invisible until a restart under load.

Forbidden OUTSIDE ``server/journal.py``:

- journal frame construction/parsing (``_frame_line`` / ``_parse_line``)
- journal segment naming (the ``"journal-"`` file prefix)

Forbidden outside ``server/journal.py`` + ``server/coordinator.py``:

- constructing the journal       (``CoordinatorJournal(...)``)
- writing records                (``record_submit/finish/prepare/
  deallocate``)
- replaying                      (``.replay(``)

Usage: ``python tools/check_journal_sites.py [src_dir]`` — exits 0 when
clean, 1 with a report. Wired into the test suite via
tests/test_elastic.py (like check_attempt_ids / check_history_sites).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: frame-level internals: only the journal module itself
_FRAME = re.compile(r"\b_(frame|parse)_line\s*\(|[\"']journal-")

#: the record/replay API: journal module + the audited consumer
_CONSUMER = re.compile(
    r"\bCoordinatorJournal\s*\("
    r"|\brecord_(submit|finish|prepare|deallocate)\s*\("
    r"|\.replay\s*\("
)

FRAME_ALLOWED = {os.path.join("server", "journal.py")}
CONSUMER_ALLOWED = FRAME_ALLOWED | {
    os.path.join("server", "coordinator.py")
}


def scan(src_dir: str) -> List[Tuple[str, int, str, str]]:
    """(path, line, kind, source-line) for every journal site outside
    its audited module(s)."""
    out: List[Tuple[str, int, str, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if rel not in FRAME_ALLOWED and _FRAME.search(line):
                        out.append((path, lineno, "frame", stripped))
                        continue
                    if rel not in CONSUMER_ALLOWED and _CONSUMER.search(
                        line
                    ):
                        out.append((path, lineno, "consumer", stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_journal_sites: journal frames confined to "
            "server/journal.py (consumer: server/coordinator.py)"
        )
        return 0
    for path, lineno, kind, line in sites:
        print(f"AD-HOC JOURNAL SITE ({kind}): {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc journal site(s) — route them through "
        "presto_tpu.server.journal instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
