#!/usr/bin/env python
"""Plan-parameterization lint: literal hoisting, RuntimeParam /
BoundParam construction, and compile-cache (``_compiled``) keying are
owned by ``presto_tpu/plan/canonical.py`` plus the audited consumers
(plan/planner.py, expr.py, sql/ast.py, exec/local_runner.py).

Shim over the unified AST framework (``tools/analysis``, rule
``plan-params`` — the compile-plane invariant checker, which resolves
calls structurally instead of line-scrubbing). Exits 0 when clean, 1
with a report. Run every pass at once with ``tools/analyze.py``;
wired into the test suite via tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "plan-params"


def scan(src_dir):
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_plan_params: literal hoisting / RuntimeParam / "
            "compile-key construction confined to plan/canonical.py "
            "(+ audited consumers)"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC PLAN PARAM: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc parameterization site(s) — route them "
        "through presto_tpu.plan.canonical instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
