#!/usr/bin/env python
"""Plan-parameterization lint: literal hoisting, RuntimeParam
construction, and compile-cache keying are owned by
``presto_tpu/plan/canonical.py`` (plus the two audited consumers noted
below) — the one module that knows the eligibility rules.

Why this matters: a RuntimeParam constructed ad hoc bypasses the
dtype/structure bucketing (strings resolve literal ids against
trace-time dictionaries, long decimals take literal-introspection fast
paths, NULLs are program structure) and silently miscompiles; a
compile-cache key assembled outside ``LocalQueryRunner._run_with_pages``
can bake literals back into the key and quietly re-open the
compile-per-literal-variant hole this plane closed; and an
``ast.BoundParam`` minted outside the canonicalizer breaks the
ordinal <-> value correspondence the statement cache binds by.

Allowed sites:
- ``plan/canonical.py`` — the canonicalizer (everything);
- ``plan/planner.py`` — the ONE BoundParam -> RuntimeParam lowering;
- ``expr.py`` — the RuntimeParam class definition + its lowering;
- ``exec/local_runner.py`` — the ``_compiled`` cache itself.

Usage: ``python tools/check_plan_params.py [src_dir]`` — exits 0 when
clean, 1 with a report listing every offending site. Wired into the
test suite via tests/test_plan_cache.py (the same pattern as
tools/check_device_puts.py in tests/test_staging_cache.py).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: (pattern, allowed relative paths)
_RULES = [
    # RuntimeParam construction (reading isinstance(...) is fine:
    # match only call-shaped spellings)
    (
        re.compile(r"\bRuntimeParam\s*\("),
        {
            os.path.join("plan", "canonical.py"),
            os.path.join("plan", "planner.py"),
            "expr.py",
        },
    ),
    # BoundParam construction outside the AST canonicalizer
    (
        re.compile(r"\bBoundParam\s*\("),
        {os.path.join("plan", "canonical.py"), os.path.join("sql", "ast.py")},
    ),
    # compile-cache key construction / direct store access (exactly
    # the runner's ``_compiled`` store; the mesh path's _frag_compiled
    # is a different cache with its own keying)
    (
        re.compile(r"(?<![A-Za-z0-9_])_compiled\s*[\[\.]"),
        {os.path.join("exec", "local_runner.py")},
    ),
    # the hoisting pass itself (its output feeds the compile-cache key;
    # calling it elsewhere forks the canonical form)
    (
        re.compile(r"\bhoist_params\s*\("),
        {
            os.path.join("plan", "canonical.py"),
            os.path.join("exec", "local_runner.py"),
        },
    ),
]

#: read-only mentions that are NOT construction/keying
_EXEMPT_LINE = re.compile(
    r"isinstance\s*\(|len\s*\(\s*self\._compiled\s*\)|"
    r"self\._runner\._compiled"
)


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if _EXEMPT_LINE.search(line):
                        continue
                    for pat, allowed in _RULES:
                        if rel in allowed:
                            continue
                        if pat.search(line):
                            out.append((path, lineno, stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_plan_params: literal hoisting / RuntimeParam / "
            "compile-key construction confined to plan/canonical.py "
            "(+ audited consumers)"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC PLAN PARAM: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc parameterization site(s) — route them "
        "through presto_tpu.plan.canonical instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
