"""Primitive-cost microbenchmarks on the live chip.

Isolates the building blocks of the Q1 device program so the aggregate
kernel design can be chosen from measured numbers, not guesses
(BASELINE.md perf breakdown; VERDICT r2 item 1):

- plain reductions per dtype (i32/i64/f32/f64): the emulation tax
- one-hot masked reduce (cap, nseg) per dtype: the current agg shape
- matmul one-hot (oh.T @ x) per dtype: the MXU alternative
- chunked scan reduce: bounded-memory alternative
- gather/sort/cumsum: sorted-path primitives

Usage: python tools/microbench_tpu.py [--cap 8388608] [--nseg 12]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import presto_tpu  # noqa: F401,E402  enables jax x64 — without it the
# i64/f64 rows would silently measure int32/float32


def bench(fn, *args, iters=5):
    import jax

    fn = jax.jit(fn)
    out = jax.block_until_ready(fn(*args))  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cap", type=int, default=8 * 1024 * 1024)
    ap.add_argument("--nseg", type=int, default=12)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    cap, nseg = args.cap, args.nseg
    print("devices:", jax.devices(), " cap:", cap, " nseg:", nseg)
    rng = np.random.RandomState(0)
    gid_np = rng.randint(0, nseg, size=cap).astype(np.int32)
    gid = jnp.asarray(gid_np)
    live = jnp.asarray(rng.rand(cap) < 0.97)

    for name, arr in [
        ("i32", jnp.asarray(rng.randint(0, 1000, cap).astype(np.int32))),
        ("i64", jnp.asarray(rng.randint(0, 1000, cap).astype(np.int64))),
        ("f32", jnp.asarray(rng.rand(cap).astype(np.float32))),
        ("f64", jnp.asarray(rng.rand(cap).astype(np.float64))),
    ]:
        t_sum = bench(lambda x: jnp.sum(x), arr)

        def onehot(x, g=gid):
            oh = g[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]
            return jnp.sum(jnp.where(oh, x[:, None], x.dtype.type(0)), axis=0)

        t_oh = bench(onehot, arr)

        def chunked(x, g=gid_np):
            import jax.lax as lax

            nchunk = 64
            csize = cap // nchunk
            xr = x.reshape(nchunk, csize)
            gr = jnp.asarray(g).reshape(nchunk, csize)

            def body(acc, xg):
                xc, gc = xg
                oh = gc[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]
                return acc + jnp.sum(
                    jnp.where(oh, xc[:, None], x.dtype.type(0)), axis=0
                ), None

            acc0 = jnp.zeros((nseg,), x.dtype)
            out, _ = lax.scan(body, acc0, (xr, gr))
            return out

        t_chunk = bench(chunked, arr)

        if name in ("f32",):
            def mm(x, g=gid):
                oh = (
                    g[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]
                ).astype(jnp.float32)
                return x @ oh

            t_mm = bench(mm, arr)
        else:
            t_mm = float("nan")
        print(
            f"{name}: sum {t_sum * 1e3:7.2f}ms  onehot {t_oh * 1e3:7.2f}ms  "
            f"chunked {t_chunk * 1e3:7.2f}ms  matmul {t_mm * 1e3:7.2f}ms"
        )

    # where/select + compaction primitives
    f64 = jnp.asarray(rng.rand(cap))
    i64 = jnp.asarray(rng.randint(0, 1000, cap).astype(np.int64))
    t = bench(lambda m, x: jnp.where(m, x, 0.0), live, f64)
    print(f"where f64: {t * 1e3:7.2f}ms")
    t = bench(lambda x: jnp.cumsum(x), i64)
    print(f"cumsum i64: {t * 1e3:7.2f}ms")
    t = bench(lambda x: jnp.cumsum(x.astype(jnp.int32)), gid)
    print(f"cumsum i32: {t * 1e3:7.2f}ms")
    t = bench(lambda x: x[jnp.argsort(gid)], f64)
    print(f"argsort-gather by i32 key (f64 payload): {t * 1e3:7.2f}ms")
    # comparison ops on i64 (filter predicates)
    t = bench(lambda x: (x < 500) & (x > 2), i64)
    print(f"i64 compare pair: {t * 1e3:7.2f}ms")
    t = bench(lambda x: x * x + x, i64)
    print(f"i64 mul+add: {t * 1e3:7.2f}ms")
    t = bench(lambda x: x * x + x, f64)
    print(f"f64 mul+add: {t * 1e3:7.2f}ms")
    t = bench(lambda x: x * x + x, f64.astype(jnp.float32))
    print(f"f32 mul+add: {t * 1e3:7.2f}ms")


if __name__ == "__main__":
    main()
