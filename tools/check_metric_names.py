#!/usr/bin/env python
"""Metric-name lint: every ``REGISTRY.<kind>("name")`` call site in the
source must register each metric name with ONE kind — the registry
raises TypeError at runtime on a conflict, but only on the code path
that hits it; this lint fails the conflict at test time instead.

Usage: ``python tools/check_metric_names.py [src_dir]`` — exits 0 when
clean, 1 with a report when any name is registered under conflicting
kinds (counter vs timer vs distribution).

Wired into the test suite via tests/test_observability.py.
"""

from __future__ import annotations

import os
import re
import sys
from collections import defaultdict
from typing import Dict, Set, Tuple

#: start of a REGISTRY.counter( / .timer( / .distribution( call
_CALL_START = re.compile(r"REGISTRY\.(counter|timer|distribution)\(")
_STRING = re.compile(r"[\"']([^\"'\n]+)[\"']")

#: timer IS a distribution (TimeStat subclasses DistributionStat), but
#: the registry still type-checks exactly, so they conflict here too.


def _call_names(src: str, open_paren: int):
    """Every string literal inside the (balanced) call argument list
    starting at ``open_paren`` — covers multi-line calls and
    conditional-expression names like ``"a" if x else "b"``."""
    depth = 0
    for i in range(open_paren, len(src)):
        if src[i] == "(":
            depth += 1
        elif src[i] == ")":
            depth -= 1
            if depth == 0:
                return [
                    m.group(1)
                    for m in _STRING.finditer(src[open_paren + 1: i])
                ]
    return []


def scan(src_dir: str) -> Dict[str, Set[Tuple[str, str]]]:
    """name -> {(kind, "file:line"), ...} over every .py under src_dir."""
    sites: Dict[str, Set[Tuple[str, str]]] = defaultdict(set)
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for m in _CALL_START.finditer(src):
                kind = m.group(1)
                lineno = src.count("\n", 0, m.start()) + 1
                for name in _call_names(src, m.end() - 1):
                    sites[name].add((kind, f"{path}:{lineno}"))
    return sites


def find_conflicts(sites: Dict[str, Set[Tuple[str, str]]]):
    out = []
    for name, entries in sorted(sites.items()):
        kinds = {k for k, _ in entries}
        if len(kinds) > 1:
            out.append((name, sorted(entries)))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    conflicts = find_conflicts(sites)
    if not conflicts:
        print(
            f"check_metric_names: {len(sites)} metric name(s), "
            "no kind conflicts"
        )
        return 0
    for name, entries in conflicts:
        print(f"CONFLICT: metric {name!r} registered as:")
        for kind, where in entries:
            print(f"  {kind:<12} at {where}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
