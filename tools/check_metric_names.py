#!/usr/bin/env python
"""Metric-name lint: every ``REGISTRY.<kind>("name")`` call site in the
source must register each metric name with ONE kind — the registry
raises TypeError at runtime on a conflict, but only on the code path
that hits it; this lint fails the conflict at analysis time instead.

Shim over the unified AST framework (``tools/analysis``, rule
``metric-names``). The AST pass also resolves names registered through
a loop variable over a literal tuple (the PR 7-9 counter families —
history.*, journal.*, pool.*, memory.*, spill.* — register that way),
which the regex predecessor silently skipped. Exits 0 when clean, 1
with a report. Run every pass at once with ``tools/analyze.py``;
wired into the test suite via tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import core, legacy  # noqa: E402
from analysis import metric_names as _pass  # noqa: E402


def scan(src_dir: str) -> Dict[str, Set[Tuple[str, str]]]:
    """name -> {(kind, "file:line"), ...} over every .py under
    src_dir (the legacy shape)."""
    modules, _errs = core.load_modules(src_dir)
    sites = _pass.collect_sites(modules)
    return {
        name: {
            (kind, f"{os.path.join(src_dir, rel)}:{line}")
            for kind, rel, line in entries
        }
        for name, entries in sites.items()
    }


def find_conflicts(sites: Dict[str, Set[Tuple[str, str]]]):
    out = []
    for name, entries in sorted(sites.items()):
        kinds = {k for k, _ in entries}
        if len(kinds) > 1:
            out.append((name, sorted(entries)))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    conflicts = find_conflicts(sites)
    if not conflicts:
        print(
            f"check_metric_names: {len(sites)} metric name(s), "
            "no kind conflicts"
        )
        return 0
    for name, entries in conflicts:
        print(f"CONFLICT: metric {name!r} registered as:")
        for kind, where in entries:
            print(f"  {kind:<12} at {where}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
