"""Per-phase profile of the window benchmark config (VERDICT r3 weak 2:
537k rows/s with no written breakdown).

Splits one steady-state iteration of the window query (row_number +
rank over 1.5M orders) into:

  compute   device program + ONE control round trip (fetch_result=False
            path: flags + live count only — no result bytes)
  transfer  materialize_page of the full 1.5M-row result (the batched
            device->host prefix fetch)
  host      host root stage (sort/limit/output over numpy)
  e2e       full runner.execute_plan for cross-checking

The hypothesis this tool tests: the window wall is RESULT TRANSFER
(~36-48 MB through a ~9 MB/s tunnel), not window compute — i.e. a
platform wall, same class as Q1's RTT floor.

Usage: python tools/profile_window.py [--sf sf1] [--iters 3]
       [--platform cpu]
"""

import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_WINDOW = """
select o_orderkey, o_custkey,
  row_number() over (partition by o_custkey order by o_orderdate) as rn,
  rank() over (partition by o_orderpriority order by o_totalprice) as rk
from tpch.SCHEMA.orders
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", default="sf1")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from presto_tpu.exec.host_ops import apply_host_ops, peel_host_ops
    from presto_tpu.exec.local_runner import (
        LocalQueryRunner,
        materialize_page,
    )
    from presto_tpu.plan import nodes as N
    from presto_tpu.plan.optimizer import prune_columns, push_scan_constraints
    from presto_tpu.plan.planner import plan_statement
    from presto_tpu.sql import parse_statement

    runner = LocalQueryRunner()
    sql = _WINDOW.replace("SCHEMA", args.sf)
    plan = plan_statement(
        parse_statement(sql), runner.catalogs, runner.session
    )

    # warmup (stages tables, compiles)
    res = runner.execute_plan(plan)
    nrows = int(res.page.num_valid)
    print(f"result rows: {nrows}")
    bytes_out = sum(
        int(b.data.dtype.itemsize) * nrows for b in res.page.blocks
    )
    print(f"result bytes (data): {bytes_out / 1e6:.1f} MB")

    root = push_scan_constraints(prune_columns(runner._bind_params(plan)))
    host_ops = []
    if runner.session.get("host_root_stage"):
        root, host_ops = peel_host_ops(root)
    scans, pages = runner.leaf_pages(root)

    phases = {k: [] for k in ("compute", "transfer", "host", "e2e")}
    for _ in range(args.iters):
        t0 = time.perf_counter()
        page, n = runner._run_with_pages(
            root, scans, pages, fetch_result=False
        )
        phases["compute"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        host_page = materialize_page(page, n)
        phases["transfer"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        if host_ops:
            apply_host_ops(host_page, host_ops)
        phases["host"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        runner.execute_plan(plan)
        phases["e2e"].append(time.perf_counter() - t0)

    for k, v in phases.items():
        print(
            f"{k:>9}: best {min(v)*1000:8.1f} ms   "
            f"median {statistics.median(v)*1000:8.1f} ms"
        )
    best_e2e = min(phases["e2e"])
    print(f"rows/s (best e2e): {nrows / best_e2e:,.0f}")


if __name__ == "__main__":
    main()
