#!/usr/bin/env python
"""Staging-plane lint: every host->device transfer must go through
``presto_tpu/exec/staging.py`` — the one place with capacity
bucketing, split-cache lookup, memory-pool accounting, and
``staging.*`` metrics. A raw ``jax.device_put`` (or an
``jnp.asarray``/``jnp.array`` conversion of host data at the
host-boundary layers) anywhere else silently bypasses the cache and
the accountant, so this lint forbids it (mirrors
``tools/check_rpc_calls.py`` for the RPC plane).

Rules:
- ``jax.device_put(`` / bare ``device_put(`` is forbidden everywhere
  outside the allowed module — it is ALWAYS a host->device transfer.
- ``jnp.asarray(`` / ``jnp.array(`` is forbidden only under the
  host-boundary packages (``server/``, ``connectors/``,
  ``parallel/``), where arrays hold host payloads and the conversion
  IS staging. Trace-time uses inside ``ops/``/``exec/`` compile into
  device programs and are fine.

Usage: ``python tools/check_device_puts.py [src_dir]`` — exits 0 when
clean, 1 with a report listing every raw staging call site.

Wired into the test suite via tests/test_staging_cache.py.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: explicit device placement (module-qualified or bare after import-from)
_DEVICE_PUT = re.compile(r"\bdevice_put\s*\(")

#: host->device array conversion at the host-boundary layers
_JNP_CONVERT = re.compile(r"\bjnp\.(?:asarray|array)\s*\(")

#: the one module allowed to stage (relative to src_dir root)
ALLOWED = {os.path.join("exec", "staging.py")}

#: packages where ANY jnp array conversion is a staging act
HOST_BOUNDARY_DIRS = ("server", "connectors", "parallel")


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    """(path, line, source-line) for every raw staging call site
    outside the allowed module."""
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            if rel in ALLOWED:
                continue
            top = rel.split(os.sep)[0]
            check_convert = top in HOST_BOUNDARY_DIRS
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if _DEVICE_PUT.search(line) or (
                        check_convert and _JNP_CONVERT.search(line)
                    ):
                        out.append((path, lineno, stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_device_puts: no raw staging call sites outside "
            "exec/staging.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"RAW STAGING: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} raw staging call site(s) — route them through "
        "presto_tpu.exec.staging instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
