#!/usr/bin/env python
"""Staging-plane lint: every host->device transfer must go through
``presto_tpu/exec/staging.py`` — the one place with capacity
bucketing, split-cache lookup, memory-pool accounting, and
``staging.*`` metrics.

Rules (unchanged):

- ``device_put(`` is forbidden everywhere outside the allowed module;
- ``jnp.asarray(`` / ``jnp.array(`` is forbidden only under the
  host-boundary packages (``server/``, ``connectors/``,
  ``parallel/``); trace-time uses inside ``ops/``/``exec/`` compile
  into device programs and are fine.

Shim over the unified AST framework (``tools/analysis``, rule
``staging-confinement``) — exits 0 when clean, 1 with a report. Run
every pass at once with ``tools/analyze.py``; wired into the test
suite via tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "staging-confinement"


def scan(src_dir):
    """(path, line, source-line) for every raw staging call site
    outside the allowed module."""
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_device_puts: no raw staging call sites outside "
            "exec/staging.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"RAW STAGING: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} raw staging call site(s) — route them through "
        "presto_tpu.exec.staging instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
