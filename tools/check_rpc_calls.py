#!/usr/bin/env python
"""RPC-plane lint: every intra-cluster HTTP call must go through
``presto_tpu/server/rpc.py`` — the one place with config-driven
timeouts, bounded backoff retries, fault-plane hooks, and ``rpc.*``
metrics. A raw ``urllib.request.urlopen`` anywhere else silently opts
out of all of that, so this lint forbids it.

Usage: ``python tools/check_rpc_calls.py [src_dir]`` — exits 0 when
clean, 1 with a report listing every raw call site outside the
allowed module.

Wired into the test suite via tests/test_faults.py.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: raw opener spellings (module-qualified or bare after an import-from)
_RAW_CALL = re.compile(r"\burlopen\s*\(")

#: the one module allowed to open sockets (relative to src_dir root)
ALLOWED = {os.path.join("server", "rpc.py")}


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    """(path, line, source-line) for every raw urlopen call site
    outside the allowed modules."""
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            if rel in ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if _RAW_CALL.search(line):
                        out.append((path, lineno, stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_rpc_calls: no raw urlopen call sites outside "
            "server/rpc.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"RAW RPC: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} raw urlopen call site(s) — route them through "
        "presto_tpu.server.rpc instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
