#!/usr/bin/env python
"""RPC-plane lint: every intra-cluster HTTP call must go through
``presto_tpu/server/rpc.py`` — the one place with config-driven
timeouts, bounded backoff retries, fault-plane hooks, and ``rpc.*``
metrics. A raw ``urllib.request.urlopen`` anywhere else silently opts
out of all of that, so this lint forbids it.

Shim over the unified AST framework (``tools/analysis``, rule
``rpc-confinement``) — same CLI contract as ever: exits 0 when clean,
1 with a report. Run every pass at once with ``tools/analyze.py``;
wired into the test suite via tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "rpc-confinement"


def scan(src_dir):
    """(path, line, source-line) for every raw urlopen call site
    outside the allowed modules."""
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_rpc_calls: no raw urlopen call sites outside "
            "server/rpc.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"RAW RPC: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} raw urlopen call site(s) — route them through "
        "presto_tpu.server.rpc instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
