#!/usr/bin/env python
"""Dynamic-filter lint: every build-side filter SUMMARY must be
constructed in ``presto_tpu/exec/dynfilter.py`` — the one audited
module that owns bounds-in-native-dtype discipline, NDV caps,
dictionary-id remapping, merge semantics, and the wire form.

An ad-hoc ``jnp.min(jnp.where(mask, keys, fill))`` build-side bound, a
hand-rolled ``ColumnFilter``/``FilterSummary`` construction, or a
bare ``RangeSet`` constraint assembled outside the module silently
re-opens the exact bug class this plane closed (32-bit-truncated
bounds excluding matching probe rows), so this lint forbids them
everywhere else in the engine.

Usage: ``python tools/check_dynfilter_sites.py [src_dir]`` — exits 0
when clean, 1 with a report listing every offending site.

Wired into the test suite via tests/test_dynfilter.py (the same
pattern as tools/check_rpc_calls.py in tests/test_faults.py).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: forbidden spellings outside the audited module:
#: - the build-summary reduction idiom (min/max over a where-filled
#:   key column — the shape that used to live in local_runner)
#: - direct summary-object construction
#: - RangeSet constraint assembly (the split-pruning vocabulary)
_PATTERNS = [
    re.compile(r"\bjnp\.(?:min|max)\s*\(\s*jnp\.where\s*\("),
    re.compile(r"\b(?:ColumnFilter|FilterSummary)\s*\("),
    re.compile(r"\bRangeSet\s*\(\s*lo\s*="),
]

#: the one module allowed to build summaries (relative to src_dir root)
ALLOWED = {os.path.join("exec", "dynfilter.py")}


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    """(path, line, source-line) for every forbidden summary-
    construction site outside the allowed module."""
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            if rel in ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if any(p.search(line) for p in _PATTERNS):
                        out.append((path, lineno, stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_dynfilter_sites: no ad-hoc filter-summary "
            "construction outside exec/dynfilter.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC DYNFILTER: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc summary construction site(s) — build "
        "them through presto_tpu.exec.dynfilter instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
