#!/usr/bin/env python
"""Dynamic-filter lint: every build-side filter SUMMARY must be
constructed in ``presto_tpu/exec/dynfilter.py`` — the one audited
module that owns bounds-in-native-dtype discipline, NDV caps,
dictionary-id remapping, merge semantics, and the wire form.

Shim over the unified AST framework (``tools/analysis``, rule
``dynfilter-confinement``) — exits 0 when clean, 1 with a report. Run
every pass at once with ``tools/analyze.py``; wired into the test
suite via tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "dynfilter-confinement"


def scan(src_dir):
    """(path, line, source-line) for every forbidden summary-
    construction site outside the allowed module."""
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_dynfilter_sites: no ad-hoc filter-summary "
            "construction outside exec/dynfilter.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC DYNFILTER: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc summary construction site(s) — build "
        "them through presto_tpu.exec.dynfilter instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
