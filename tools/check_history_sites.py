#!/usr/bin/env python
"""History-based-statistics lint: history-record construction/parsing,
canonical node fingerprints, and the ``estimate_rows`` history lookup
are owned by ``presto_tpu/plan/history.py`` plus the audited consumers
(plan/optimizer.py, exec/local_runner.py, exec/explain.py,
server/coordinator.py).

Shim over the unified AST framework (``tools/analysis``, rule
``history-sites`` — the compile-plane invariant checker's history
half: calls are matched as calls, so attribute reads and keyword
assignments never needed scrub patterns). Exits 0 when clean, 1 with
a report. Run every pass at once with ``tools/analyze.py``; wired
into the test suite via tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "history-sites"


def scan(src_dir):
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_history_sites: history records, canonical "
            "fingerprints, and estimate-time lookups confined to "
            "plan/history.py (+ audited consumers)"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC HISTORY SITE: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc history site(s) — route them through "
        "presto_tpu.plan.history instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
