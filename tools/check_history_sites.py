#!/usr/bin/env python
"""History-based-statistics lint: history-record construction/parsing,
canonical node fingerprints, and the ``estimate_rows`` history lookup
are owned by ``presto_tpu/plan/history.py`` (plus the audited consumers
noted below).

Why this matters: a history record written outside the store bypasses
the crash-safe segment discipline (torn-line tolerance, rotation, the
bounded index) and the hit/miss/write/evict metrics; a node fingerprint
computed ad hoc forks the canonical identity (the store's keys are
literal- AND pruning-invariant — plan/history._signature is the one
place that knows which fields are cardinality-determining); and a
history lookup outside ``optimizer.estimate_rows`` silently re-opens
the estimate-provenance hole EXPLAIN labels were built to close.

Allowed sites:
- ``plan/history.py`` — the store + fingerprints (everything);
- ``plan/optimizer.py`` — the ONE estimate-time lookup;
- ``exec/local_runner.py`` — store construction (config/env wiring),
  per-compile fingerprint batches, the analyzed-run record write;
- ``exec/explain.py`` — est-vs-actual rendering fingerprints;
- ``server/coordinator.py`` — the statement-fingerprint stamp.

Usage: ``python tools/check_history_sites.py [src_dir]`` — exits 0 when
clean, 1 with a report listing every offending site. Wired into the
test suite via tests/test_history_stats.py (the same pattern as
tools/check_plan_params.py in tests/test_plan_cache.py).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

_HISTORY = os.path.join("plan", "history.py")
_RUNNER = os.path.join("exec", "local_runner.py")

#: (pattern, allowed relative paths)
_RULES = [
    # store construction: config/env wiring lives on the runner
    (
        re.compile(r"\bQueryHistoryStore\s*\("),
        {_HISTORY, _RUNNER},
    ),
    # record write (construct + persist): the store itself, plus the
    # runner's analyzed-run twin of the query-completed path
    (
        re.compile(r"\brecord_query\s*\("),
        {_HISTORY, _RUNNER},
    ),
    # the estimate-time read path: exactly optimizer.estimate_rows
    (
        re.compile(r"\blookup_rows\s*\("),
        {_HISTORY, os.path.join("plan", "optimizer.py")},
    ),
    # canonical node fingerprints: the store's key space
    (
        re.compile(r"\bnode_fingerprints?\s*\(|\bplan_fingerprint\s*\("),
        {
            _HISTORY,
            _RUNNER,
            os.path.join("exec", "explain.py"),
            os.path.join("server", "coordinator.py"),
        },
    ),
]

#: read-only mentions that are NOT construction/lookup (attribute reads
#: of the stamped QueryStats field, keyword/assignment targets, string
#: keys, isinstance checks). These are SCRUBBED from the line before
#: the rules run — a blanket line-level exemption would also swallow a
#: disallowed call on the same line (``x.plan_fingerprint =
#: plan_history.plan_fingerprint(root)`` must still flag).
_EXEMPT_SUB = re.compile(
    r"isinstance\s*\(|\.plan_fingerprint\b(?!\s*\()|"
    r"\bplan_fingerprint\s*=(?!=)|\"plan_fingerprint\""
)


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    scrubbed = _EXEMPT_SUB.sub(" ", line)
                    for pat, allowed in _RULES:
                        if rel in allowed:
                            continue
                        if pat.search(scrubbed):
                            out.append((path, lineno, stripped))
                            break  # one report per line
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_history_sites: history records, canonical "
            "fingerprints, and estimate-time lookups confined to "
            "plan/history.py (+ audited consumers)"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC HISTORY SITE: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc history site(s) — route them through "
        "presto_tpu.plan.history instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
