#!/usr/bin/env python3
"""Bench-trajectory regression gate.

Diffs consecutive ``BENCH_*.json`` artifacts (the bench driver's
``{n, cmd, rc, tail, parsed}`` capture, where ``tail`` holds the
JSONL result lines) and flags any metric that degraded by more than
the threshold (default 20%) between two consecutive rounds.

Skip discipline (the BENCH_r04/r05 lesson, see bench.py ``_emit``):

- a line with ``skipped: true`` is a skip — it carries no value and
  never participates in a comparison, in either role;
- a LEGACY line carrying ``error`` beside a value (the pre-contract
  ``value: 0`` shape r04/r05 actually shipped) is treated as skipped
  too — that zero was never a measurement and must neither flag a
  drop against the round before it nor serve as the baseline that
  makes the next real round look like an infinite improvement;
- a missing/None/non-numeric value is a skip (null-safe end to end).

Direction comes from the unit: throughput-like units (rows/s,
queries/s, qps, x, queries) regress by DROPPING; time-like units (ms,
s, seconds) regress by RISING. Unknown units default to higher-better.

Exit status: 1 if any regression was flagged, else 0. Skipped lines
alone can never fail the gate.

Usage::

    python tools/check_bench_regress.py [--threshold 0.2] [FILES...]

With no FILES, globs ``BENCH_*.json`` in the repo root (sorted, so
``_rNN`` ordering is the round ordering).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: units where a SMALLER value is the regression (hits: the serving
#: result-cache hit count — a cache that silently stopped hitting is
#: a serving regression even when raw qps survives)
_HIGHER_BETTER = {"rows/s", "queries/s", "qps", "x", "queries", "hits"}
#: units where a LARGER value is the regression (dispatches/bytes:
#: the exchange-plane device accounting — per-query dispatch counts
#: and transfer bytes regress upward)
_LOWER_BETTER = {"ms", "s", "seconds", "dispatches", "bytes"}


def is_skipped(line: dict) -> bool:
    """True when the line carries no real measurement (skip contract
    + legacy error-beside-value shape + null safety)."""
    if line.get("skipped"):
        return True
    if "error" in line:
        # pre-contract artifacts (BENCH_r04/r05): value 0 beside the
        # error — a failed measurement, not a measured zero
        return True
    value = line.get("value")
    return not isinstance(value, (int, float)) or isinstance(
        value, bool
    )


def parse_lines(tail: str) -> Dict[str, dict]:
    """Extract metric lines from a JSONL tail, last write wins
    (re-measured metrics supersede), non-JSON noise skipped."""
    out: Dict[str, dict] = {}
    for raw in tail.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            line = json.loads(raw)
        except ValueError:
            continue
        if isinstance(line, dict) and "metric" in line:
            out[line["metric"]] = line
    return out


def parse_artifact(obj: dict) -> Dict[str, dict]:
    """Metric -> result line of one BENCH_*.json capture. ``tail`` is
    authoritative; ``parsed`` (the headline line) backstops artifacts
    whose tail was truncated past the JSONL."""
    lines = parse_lines(obj.get("tail") or "")
    parsed = obj.get("parsed")
    if (
        isinstance(parsed, dict)
        and parsed.get("metric")
        and parsed["metric"] not in lines
    ):
        lines[parsed["metric"]] = parsed
    return lines


def _direction(unit: Optional[str]) -> int:
    """+1 = higher is better (drop regresses), -1 = lower is better."""
    return -1 if (unit or "") in _LOWER_BETTER else 1


def compare(
    prev: Dict[str, dict],
    cur: Dict[str, dict],
    threshold: float = 0.2,
) -> List[dict]:
    """Regressions between two rounds: metrics measured (non-skipped)
    in BOTH whose value moved against its unit's direction by more
    than ``threshold`` (relative). Returns finding dicts."""
    findings: List[dict] = []
    for metric in sorted(set(prev) & set(cur)):
        a, b = prev[metric], cur[metric]
        if is_skipped(a) or is_skipped(b):
            continue
        va, vb = float(a["value"]), float(b["value"])
        if va == 0:
            continue  # no meaningful relative change from zero
        change = (vb - va) / abs(va)
        if _direction(b.get("unit") or a.get("unit")) * change < -threshold:
            findings.append(
                {
                    "metric": metric,
                    "unit": b.get("unit") or a.get("unit"),
                    "before": va,
                    "after": vb,
                    "change_pct": round(100.0 * change, 1),
                }
            )
    return findings


def check_files(
    paths: List[str], threshold: float = 0.2
) -> Tuple[List[dict], int]:
    """Run the gate over consecutive artifact pairs; returns
    (findings, rounds_compared)."""
    rounds: List[Tuple[str, Dict[str, dict]]] = []
    for p in paths:
        try:
            with open(p) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench-regress: unreadable {p}: {e}", file=sys.stderr)
            continue
        rounds.append((p, parse_artifact(obj)))
    findings: List[dict] = []
    for (pa, a), (pb, b) in zip(rounds, rounds[1:]):
        for f in compare(a, b, threshold):
            f["from"], f["to"] = os.path.basename(pa), os.path.basename(pb)
            findings.append(f)
    return findings, max(len(rounds) - 1, 0)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*", help="BENCH_*.json artifacts, in round order")
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.2,
        help="relative degradation that flags (default 0.2 = 20%%)",
    )
    args = ap.parse_args(argv)
    paths = args.files or sorted(
        glob.glob(
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "BENCH_*.json",
            )
        )
    )
    if len(paths) < 2:
        print("bench-regress: need at least two artifacts; nothing to diff")
        return 0
    findings, pairs = check_files(paths, args.threshold)
    for f in findings:
        print(
            f"REGRESSION {f['metric']} [{f['unit']}] "
            f"{f['from']} -> {f['to']}: "
            f"{f['before']:g} -> {f['after']:g} ({f['change_pct']:+.1f}%)"
        )
    if not findings:
        print(f"bench-regress: OK ({pairs} consecutive pairs, no regressions)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
