"""Native-vs-numpy head-to-head for varlen->dictionary-id encoding.

The measured go/no-go for the C++ host-agent codec (SURVEY.md §2.3
disposition) — same discipline as tools/pallas_groupby.py: keep
whichever implementation wins, record the numbers.

Usage: python tools/bench_native.py [--rows 1000000] [--card 50000]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--card", type=int, default=50_000)
    ap.add_argument("--null-frac", type=float, default=0.02)
    args = ap.parse_args()

    from presto_tpu import native
    from presto_tpu.page import encode_strings

    rng = np.random.RandomState(0)
    pool = np.asarray(
        [f"value-{i:08d}-{rng.randint(1e9)}" for i in range(args.card)],
        dtype=object,
    )
    vals = pool[rng.randint(0, args.card, args.rows)].copy()
    nulls = rng.rand(args.rows) < args.null_frac
    vals[nulls] = None

    assert native.available(), "native build failed (g++ missing?)"

    t0 = time.perf_counter()
    ids_n, valid_n, uniq_n = native.encode_strings_native(vals)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    ids_p, valid_p, dic_p = encode_strings(vals, force_numpy=True)
    t_numpy = time.perf_counter() - t0

    assert (valid_n == valid_p).all()
    assert (ids_n[valid_n] == ids_p[valid_p]).all(), "id mismatch"
    assert list(uniq_n) == list(dic_p.values), "dictionary mismatch"
    print(
        f"rows={args.rows} card={args.card}  "
        f"numpy {t_numpy * 1e3:8.1f} ms   "
        f"native {t_native * 1e3:8.1f} ms   "
        f"speedup {t_numpy / t_native:5.2f}x"
    )

    # closed-form generator (native/genstream.cpp): fused stream loop
    # vs numpy's 6-pass vectorized mix
    from presto_tpu.connectors import tpch

    assert native._load_gen() is not None, "genstream build failed"
    n = args.rows * 10
    idx = np.arange(n, dtype=np.int64)
    tpch._uniform(1701, idx, 1, 200000)  # warm
    t0 = time.perf_counter()
    got_native = tpch._uniform(1701, idx, 1, 200000)
    t_native = time.perf_counter() - t0
    saved = native._gen_lib
    native._gen_lib = None
    try:
        tpch._uniform(1701, idx, 1, 200000)  # warm
        t0 = time.perf_counter()
        got_numpy = tpch._uniform(1701, idx, 1, 200000)
        t_numpy = time.perf_counter() - t0
    finally:
        native._gen_lib = saved
    assert np.array_equal(got_native, got_numpy)
    print(
        f"gen_uniform rows={n}  "
        f"numpy {t_numpy * 1e3:8.1f} ms ({n / t_numpy / 1e6:.0f}M/s)   "
        f"native {t_native * 1e3:8.1f} ms ({n / t_native / 1e6:.0f}M/s)  "
        f"speedup {t_numpy / t_native:5.2f}x"
    )


if __name__ == "__main__":
    main()
