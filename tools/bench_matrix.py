"""Benchmark-matrix wrapper: one process per config, honest rc.

Runs every ``bench.py --only <config>`` in its OWN subprocess (a tunnel
backend crash on one config must not poison the rest — BASELINE.md
"matrix walls") and records an HONEST status per config: a config
counts as failed when the subprocess exits nonzero, times out, OR its
JSON line carries an ``error``/zero value (VERDICT r3 weak 1: the old
wrapper conflated "process exited" with "measurement succeeded").

Usage:
    python tools/bench_matrix.py [--timeout SECONDS] [CONFIG ...]

Outputs tools/benchout/<config>.jsonl + .err per config and a summary
``progress.log``; exits nonzero if any config failed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tools", "benchout")

#: default matrix = every --all config, cheapest first so a late crash
#: loses the least
CONFIGS = [
    "q3_sf1",
    "q5_sf1",
    "q18_sf1_rows",
    "q18_sf1_streamed",
    "window",
    "tpcds_q95",
    "tpcds_q64",
    "tpcds_q72_sf1",
    "q3_sf10",
    "q5_sf10",
    "q18_sf10",
]


def run_config(config: str, timeout: float) -> tuple[int, str]:
    """-> (rc, status) where status is ok|error|crash|timeout."""
    os.makedirs(OUT, exist_ok=True)
    out_path = os.path.join(OUT, f"{config}.jsonl")
    err_path = os.path.join(OUT, f"{config}.err")
    with open(out_path, "w") as out, open(err_path, "w") as err:
        try:
            proc = subprocess.run(
                [sys.executable, "-u", "bench.py", "--only", config],
                cwd=REPO,
                stdout=out,
                stderr=err,
                timeout=timeout,
            )
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            return 124, "timeout"
    status = "ok" if rc == 0 else ("crash" if rc < 0 else "error")
    try:
        with open(out_path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        if not lines:
            status = "crash" if rc != 0 else "error"
        for rec in lines:
            if rec.get("error") or not rec.get("value"):
                status = "error" if rc == 0 else status
                rc = rc or 1
    except (json.JSONDecodeError, OSError):
        status, rc = "crash", rc or 1
    return rc, status


def main() -> None:
    args = sys.argv[1:]
    timeout = 2400.0
    if "--timeout" in args:
        i = args.index("--timeout")
        timeout = float(args[i + 1])
        del args[i: i + 2]
    configs = args or CONFIGS
    log_path = os.path.join(OUT, "progress.log")
    os.makedirs(OUT, exist_ok=True)
    any_failed = False
    with open(log_path, "w") as log:
        for c in configs:
            rc, status = run_config(c, timeout)
            line = f"=== {c} rc={rc} status={status}"
            print(line, flush=True)
            log.write(line + "\n")
            log.flush()
            any_failed |= status != "ok"
        log.write("ALL-DONE\n")
    sys.exit(1 if any_failed else 0)


if __name__ == "__main__":
    main()
