"""Pallas-vs-XLA head-to-head for the grouped-aggregation hot path.

VERDICT r2 item 9: either ship a Pallas kernel where XLA's lowering
demonstrably loses, or record the measured case against it. The
candidate is the one-hot grouped sum (ops.aggregation._onehot_aggregate
— Q1's shape: ~8M rows, ~12 segments):

- ``xla_onehot``  — the engine's current composition: broadcast compare
  + masked sum, fused by XLA.
- ``pallas_onehot`` — hand-blocked VMEM kernel: rows stream through VMEM
  in (BLOCK, 128) tiles, an (nseg, 128) accumulator lives in VMEM across
  grid steps, per-segment masked sums unrolled on the VPU.

Both are timed with forced device_get sync, with the measured null
round trip subtracted (the axon tunnel costs ~65 ms per sync — see
BASELINE.md round-3 breakdown). Numerical parity is asserted against a
float64 numpy reference first.

Usage: python tools/pallas_groupby.py [--rows 8388608] [--nseg 12]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8 * 1024 * 1024)
    ap.add_argument("--nseg", type=int, default=12)
    ap.add_argument("--block", type=int, default=2048)
    ap.add_argument(
        "--x64", action="store_true",
        help="run under the engine's jax_enable_x64=True config — "
        "reproduces the Mosaic 'failed to legalize func.return' compile "
        "failure (i64 leaks into the kernel), which is itself finding #1 "
        "against Pallas here: the engine's int64/float64 SQL semantics "
        "and Mosaic do not currently coexist",
    )
    args = ap.parse_args()

    if args.x64:
        import jax

        jax.config.update("jax_enable_x64", True)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, nseg, BLOCK = args.rows, args.nseg, args.block
    assert rows % 128 == 0
    M = rows // 128
    assert M % BLOCK == 0

    rng = np.random.RandomState(0)
    x_np = rng.rand(rows).astype(np.float32)
    g_np = rng.randint(0, nseg, rows).astype(np.int32)
    ref = np.array(
        [x_np[g_np == s].astype(np.float64).sum() for s in range(nseg)]
    )

    x = jnp.asarray(x_np)
    g = jnp.asarray(g_np)

    def xla_onehot(x, g):
        oh = g[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]
        return jnp.sum(jnp.where(oh, x[:, None], jnp.float32(0)), axis=0)

    x2 = x.reshape(M, 128)
    g2 = g.reshape(M, 128)

    def kernel(x_ref, g_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        xb = x_ref[:]
        gb = g_ref[:]
        partial = [
            jnp.sum(
                jnp.where(gb == jnp.int32(s), xb, jnp.float32(0)), axis=0
            )
            for s in range(nseg)
        ]
        out_ref[:] = out_ref[:] + jnp.stack(partial)

    def pallas_onehot(x2, g2):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((nseg, 128), jnp.float32),
            grid=(M // BLOCK,),
            in_specs=[
                pl.BlockSpec(
                    (BLOCK, 128), lambda i: (i, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (BLOCK, 128), lambda i: (i, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (nseg, 128), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        )(x2, g2)
        return jnp.sum(out, axis=1)

    def sync(y):
        return jax.device_get(y)

    def bench(fn, *a, iters=7):
        f = jax.jit(fn)
        out = sync(f(*a))
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            sync(f(*a))
            ts.append(time.perf_counter() - t0)
        return out, min(ts)

    # null round trip: fetch a tiny precomputed value
    tiny = jnp.zeros((1,), jnp.float32)
    _, t_null = bench(lambda t: t + 1, tiny)

    out_x, t_x = bench(xla_onehot, x, g)
    out_p, t_p = bench(pallas_onehot, x2, g2)

    err_x = np.abs(np.asarray(out_x, np.float64) - ref).max() / ref.max()
    err_p = np.abs(np.asarray(out_p, np.float64) - ref).max() / ref.max()
    print(f"devices: {jax.devices()}  rows={rows} nseg={nseg}")
    print(f"null sync round trip:      {t_null * 1e3:8.2f} ms")
    print(
        f"XLA one-hot composition:   {t_x * 1e3:8.2f} ms "
        f"(-null: {(t_x - t_null) * 1e3:7.2f} ms)  max rel err {err_x:.2e}"
    )
    print(
        f"Pallas VMEM-blocked:       {t_p * 1e3:8.2f} ms "
        f"(-null: {(t_p - t_null) * 1e3:7.2f} ms)  max rel err {err_p:.2e}"
    )
    assert err_x < 1e-5 and err_p < 1e-5, "parity failure"
    hbm = rows * 8 / 1e9  # f32 data + i32 gid
    print(
        f"roofline (HBM {hbm:.2f} GB @ ~800 GB/s): {hbm / 800 * 1e3:.2f} ms"
    )


if __name__ == "__main__":
    main()
