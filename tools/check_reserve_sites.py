#!/usr/bin/env python
"""Reserve-site lint: memory-pool reservations must be confined to
``presto_tpu/utils/memory.py`` (the one audited module) plus the
audited consumers below.

Cluster memory governance hangs on the accounting being COMPLETE: the
workers' heartbeat reports, the arbiter's quotas, the low-memory
killer's victim selection, and the "pools drain to zero" invariant all
read ``MemoryPool`` state. An ad-hoc ``reserve`` call (or a second
pool constructed on the side) elsewhere would hold device bytes the
cluster view cannot see — invisible residency that breaks victim
selection and leak detection exactly when memory is scarcest.

Forbidden OUTSIDE ``utils/memory.py`` + the audited consumers:

- pool construction            (``MemoryPool(...)``)
- reserving                    (``.reserve(`` / ``.try_reserve(``)

Audited consumers:

- ``exec/staging.py``      — the split cache's try_reserve discipline
- ``exec/local_runner.py`` — staged-page residency accounting
- ``server/worker.py``     — task buffers + merge-build staging
- ``server/coordinator.py``— pool construction (kill-largest policy)

Usage: ``python tools/check_reserve_sites.py [src_dir]`` — exits 0
when clean, 1 with a report. Wired into the test suite via
tests/test_memory_governance.py (the same confinement pattern as
check_rpc_calls / check_journal_sites).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: a reservation call or a pool construction
_RESERVE = re.compile(
    r"\.(?:try_)?reserve\s*\(|\bMemoryPool\s*\("
)

ALLOWED = {
    os.path.join("utils", "memory.py"),
    os.path.join("exec", "staging.py"),
    os.path.join("exec", "local_runner.py"),
    os.path.join("server", "worker.py"),
    os.path.join("server", "coordinator.py"),
}


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    """(path, line, source-line) for every reserve site outside the
    audited modules."""
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            if rel in ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if _RESERVE.search(line):
                        out.append((path, lineno, stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_reserve_sites: pool reservations confined to "
            "utils/memory.py + audited consumers"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC RESERVE SITE: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc reserve site(s) — route them through "
        "presto_tpu.utils.memory's audited consumers instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
