#!/usr/bin/env python
"""Reserve-site lint: memory-pool reservations must be confined to
``presto_tpu/utils/memory.py`` (the one audited module) plus the
audited consumers (exec/staging.py, exec/local_runner.py,
server/worker.py, server/coordinator.py). An ad-hoc reserve elsewhere
holds device bytes the cluster view cannot see.

Shim over the unified AST framework (``tools/analysis``, rule
``reserve-sites``) — exits 0 when clean, 1 with a report. Run every
pass at once with ``tools/analyze.py``; wired into the test suite via
tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "reserve-sites"


def scan(src_dir):
    """(path, line, source-line) for every reserve site outside the
    audited modules."""
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_reserve_sites: pool reservations confined to "
            "utils/memory.py + audited consumers"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC RESERVE SITE: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc reserve site(s) — route them through "
        "presto_tpu.utils.memory's audited consumers instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
