#!/usr/bin/env python
"""Attempt-id lint: task/attempt-id construction and parsing must be
confined to ``presto_tpu/server/task_ids.py`` — the one audited module.

Fault-tolerant execution keys the durable exchange spool by
deterministic task-attempt ids, and recovery is only correct when
exactly one attempt's pages are consumed per logical task. An ad-hoc
f-string task id or a bare ``task_id.split(...)`` elsewhere would
silently break that dedup (a replacement attempt would stop sharing its
original's logical key, or a parser would mis-read the attempt field).

Forbidden outside the audited module:

- constructing a task id from an f-string  (``task_id=f"..."``)
- string-parsing a task id                 (``task_id.split(...)``,
  ``src_task.rsplit(...)``, partition/rpartition likewise)

Usage: ``python tools/check_attempt_ids.py [src_dir]`` — exits 0 when
clean, 1 with a report. Wired into the test suite via
tests/test_spool.py (like check_rpc_calls / check_dynfilter_sites).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: ad-hoc construction: any f-string assigned to a task_id variable or
#: keyword argument
_CONSTRUCT = re.compile(r"\btask_id\s*=\s*f[\"']")

#: ad-hoc parsing: string-splitting a task id (by any spelling the
#: codebase uses for one)
_PARSE = re.compile(
    r"\b(task_id|src_task|tid)\s*\.\s*(r?split|r?partition)\s*\("
)

#: the one module allowed to construct/parse (relative to src_dir root)
ALLOWED = {os.path.join("server", "task_ids.py")}


def scan(src_dir: str) -> List[Tuple[str, int, str]]:
    """(path, line, source-line) for every ad-hoc task-id construction
    or parse site outside the audited module."""
    out: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir)
            if rel in ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    stripped = line.strip()
                    if stripped.startswith("#"):
                        continue
                    if _CONSTRUCT.search(line) or _PARSE.search(line):
                        out.append((path, lineno, stripped))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )
    sites = scan(src_dir)
    if not sites:
        print(
            "check_attempt_ids: task-id construction/parsing confined "
            "to server/task_ids.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC TASK ID: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc task-id site(s) — route them through "
        "presto_tpu.server.task_ids instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
