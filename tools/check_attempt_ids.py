#!/usr/bin/env python
"""Attempt-id lint: task/attempt-id construction and parsing must be
confined to ``presto_tpu/server/task_ids.py`` — the one audited module.
Recovery is only correct when exactly one attempt's pages are consumed
per logical task; an ad-hoc f-string task id or a bare
``task_id.split(...)`` elsewhere silently breaks that dedup.

Shim over the unified AST framework (``tools/analysis``, rule
``attempt-ids``) — exits 0 when clean, 1 with a report. Run every
pass at once with ``tools/analyze.py``; wired into the test suite via
tests/test_static_analysis.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import legacy  # noqa: E402

RULE = "attempt-ids"


def scan(src_dir):
    """(path, line, source-line) for every ad-hoc task-id construction
    or parse site outside the audited module."""
    return legacy.shim_scan(RULE, src_dir)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = args[0] if args else legacy.default_src()
    sites = scan(src_dir)
    if not sites:
        print(
            "check_attempt_ids: task-id construction/parsing confined "
            "to server/task_ids.py"
        )
        return 0
    for path, lineno, line in sites:
        print(f"AD-HOC TASK ID: {path}:{lineno}: {line}")
    print(
        f"{len(sites)} ad-hoc task-id site(s) — route them through "
        "presto_tpu.server.task_ids instead"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
