"""Shared AST-analysis framework: the loader, the pass registry, the
finding type, inline suppressions, and the JSON/baseline plumbing that
``tools/analyze.py`` and every ``tools/check_*.py`` shim sit on.

Design:

- every pass parses, never imports — the framework must run without
  jax (and without executing any engine code) so it can gate merges
  from any environment;
- a *pass* is a function ``(modules, src_dir) -> [Finding]`` registered
  under a stable rule id via :func:`register`;
- suppression is per line: a trailing ``# lint: disable=<rule>[,rule]``
  on the finding's line keeps the finding in the JSON (marked
  ``suppressed``) but out of the exit code;
- a *baseline* file (``tools/analyze.py --baseline``) demotes exact
  known findings to warn-only so a new pass can be introduced before
  the tree is clean under it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: inline suppression: ``# lint: disable=rule-a,rule-b`` on the line
SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_\-, ]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation anchored to a source line."""

    rule: str
    path: str  #: path as reported (src_dir-joined, like the legacy lints)
    rel: str  #: path relative to the analyzed root (stable across hosts)
    line: int
    message: str
    snippet: str = ""
    #: set by the framework when the line carries a matching disable
    suppressed: bool = False
    #: set by a pass when an audited allowlist entry covers the site
    allowlisted: bool = False
    justification: str = ""
    #: set by the driver when a baseline file covers the finding
    baselined: bool = False

    @property
    def active(self) -> bool:
        """True when the finding should fail the run."""
        return not (self.suppressed or self.allowlisted or self.baselined)

    def key(self) -> str:
        """Stable identity used by baseline files."""
        return f"{self.rule}|{self.rel}|{self.line}"

    def to_json(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.rel,
            "line": self.line,
            "message": self.message,
        }
        if self.snippet:
            d["snippet"] = self.snippet
        if self.suppressed:
            d["suppressed"] = True
        if self.allowlisted:
            d["allowlisted"] = True
            d["justification"] = self.justification
        if self.baselined:
            d["baselined"] = True
        return d


class Module:
    """One parsed source file plus its raw lines and suppressions."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._nodes = None  # lazy flat node list shared by passes
        #: line -> set of suppressed rule ids
        self.suppressions: Dict[int, set] = {}
        for i, ln in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(ln)
            if m:
                self.suppressions[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }

    @property
    def nodes(self):
        """Every AST node of the module, flattened once — passes that
        just scan for node shapes iterate this instead of re-walking
        the tree (the walk dominated analysis time otherwise)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, line: int, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.path,
            rel=self.rel,
            line=line,
            message=message,
            snippet=self.snippet(line),
        )


def load_modules(src_dir: str) -> Tuple[List[Module], List[Finding]]:
    """Parse every ``.py`` under ``src_dir``. Unparseable files become
    ``parse-error`` findings — nothing is silently skipped."""
    modules: List[Module] = []
    errors: List[Finding] = []
    for root, dirs, files in os.walk(src_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, src_dir).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            except OSError as e:
                errors.append(
                    Finding("parse-error", path, rel, 0, f"unreadable: {e}")
                )
                continue
            try:
                modules.append(Module(path, rel, source))
            except SyntaxError as e:
                errors.append(
                    Finding(
                        "parse-error",
                        path,
                        rel,
                        int(e.lineno or 0),
                        f"syntax error: {e.msg}",
                    )
                )
    return modules, errors


# --------------------------------------------------------------- registry


@dataclasses.dataclass
class Pass:
    rule: str
    run: Callable[[List[Module], str], List[Finding]]
    doc: str = ""


#: rule id -> Pass, in registration order (stable CLI/report order)
PASSES: "Dict[str, Pass]" = {}


def register(rule: str, doc: str = ""):
    """Decorator: publish a pass under a stable rule id."""

    def deco(fn):
        PASSES[rule] = Pass(rule=rule, run=fn, doc=doc)
        return fn

    return deco


def _ensure_passes_loaded() -> None:
    """Import every pass module exactly once (registration side
    effect). Local imports avoid a cycle with the pass modules, which
    import :mod:`core` themselves."""
    from analysis import confinement  # noqa: F401
    from analysis import locks  # noqa: F401
    from analysis import metric_names  # noqa: F401
    from analysis import plane  # noqa: F401


def all_rules() -> List[str]:
    _ensure_passes_loaded()
    return list(PASSES)


def run_passes(
    src_dir: str,
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[set] = None,
) -> List[Finding]:
    """Run the selected passes (default: all) over ``src_dir`` and
    return every finding, suppression/baseline flags applied, sorted
    by (path, line, rule)."""
    _ensure_passes_loaded()
    modules, findings = load_modules(src_dir)
    by_rel = {m.rel: m for m in modules}
    selected = list(rules) if rules else list(PASSES)
    for rule in selected:
        if rule not in PASSES:
            raise KeyError(
                f"unknown rule {rule!r} (known: {', '.join(PASSES)})"
            )
        findings.extend(PASSES[rule].run(modules, src_dir))
    for f in findings:
        mod = by_rel.get(f.rel)
        if mod is not None and f.rule in mod.suppressions.get(f.line, ()):
            f.suppressed = True
        if baseline and f.key() in baseline:
            f.baselined = True
    findings.sort(key=lambda f: (f.rel, f.line, f.rule, f.message))
    return findings


# --------------------------------------------------------------- reports


def to_json(findings: List[Finding], src_dir: str) -> str:
    """Stable (diffable) JSON: sorted findings, no timestamps."""
    doc = {
        "version": 1,
        "rules": all_rules(),
        "findings": [f.to_json() for f in findings],
        "counts": {
            "total": len(findings),
            "active": sum(1 for f in findings if f.active),
            "suppressed": sum(1 for f in findings if f.suppressed),
            "allowlisted": sum(1 for f in findings if f.allowlisted),
            "baselined": sum(1 for f in findings if f.baselined),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def load_baseline(path: str) -> set:
    """Baseline file: the ``baseline`` list written by
    ``analyze.py --write-baseline`` (finding keys, one per entry)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return set(doc.get("baseline", ()))
    return set(doc)


def write_baseline(path: str, findings: List[Finding]) -> None:
    keys = sorted(
        f.key()
        for f in findings
        if not (f.suppressed or f.allowlisted)
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "baseline": keys}, f, indent=2)
        f.write("\n")


# ----------------------------------------------------- AST conveniences


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call's callee (None for computed callees)."""
    return dotted_name(call.func)


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last identifier of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def str_constants(node: ast.AST) -> List[str]:
    """Every string literal anywhere under ``node``."""
    return [
        n.value
        for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the class/function qualname stack.

    Subclasses read ``self.class_stack`` / ``self.func_stack`` /
    :meth:`qualname` and may override ``visit_*`` as usual; they must
    call ``self.generic_visit(node)`` to descend."""

    def __init__(self):
        self.class_stack: List[str] = []
        self.func_stack: List[str] = []

    @property
    def current_class(self) -> Optional[str]:
        return self.class_stack[-1] if self.class_stack else None

    def qualname(self) -> str:
        return ".".join(self.class_stack + self.func_stack) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_func(node)
