"""Legacy-shim support: the nine ``tools/check_*.py`` CLIs keep their
exact command-line contract (exit 0 clean / 1 with a report, same
``scan()`` tuple shapes) but every rule now runs exactly once, inside
the framework — no duplicated logic left behind the shims."""

from __future__ import annotations

import os
from typing import List

from analysis import core


def default_src() -> str:
    """The repo's presto_tpu package (the legacy lints' default)."""
    return os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        "presto_tpu",
    )


def shim_findings(rule: str, src_dir: str) -> List[core.Finding]:
    """Active findings of one rule over ``src_dir`` (suppressed and
    allowlisted sites stay out of a shim's report, exactly like the
    one CLI)."""
    return [
        f
        for f in core.run_passes(src_dir, rules=[rule])
        if f.rule == rule and f.active
    ]


def shim_scan(rule: str, src_dir: str):
    """Legacy ``scan()`` shape: (path, lineno, stripped-source-line)."""
    return [
        (f.path, f.line, f.snippet) for f in shim_findings(rule, src_dir)
    ]
