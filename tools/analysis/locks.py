"""Concurrency-discipline passes: ``lock-order`` and
``blocking-under-lock``.

Both sit on one shared model built per run:

1. **Lock identities.** Every ``threading.Lock/RLock/Condition``
   creation site is collected into a registry — class attributes
   (``self._lock = threading.Lock()`` and dataclass
   ``field(default_factory=threading.Lock)``), module-level names, and
   function-local names. A ``Condition(self._lock)`` aliases the
   underlying lock (one identity, not two). Identities are per
   (module, class, attr) — instance-distinct locks of one class share
   an identity, which over-approximates; suppress deliberate cases
   inline.

2. **Held-set tracking.** Each function body is walked with the stack
   of currently-held locks (``with`` nesting; ``.acquire()`` emits an
   acquisition event without extending the held set — releases are
   not tracked). ``with`` expressions that *name a known lock
   attribute* but cannot be pinned to one class still count as held
   (they gate blocking findings) without feeding graph edges.

3. **Call edges.** Calls are resolved intra-module (bare names,
   ``self.method``, ``Class.method``), through ``presto_tpu`` import
   aliases (``rpc.call_json`` -> server/rpc.py), and through a
   globally-unique-method fallback (skipped for common container verbs
   — see ``_METHOD_DENYLIST``). Per-function summaries of
   *may-acquire* and *may-block* propagate through the resolved call
   graph to a fixpoint.

**lock-order** builds the held-while-acquiring digraph (direct nesting
plus call edges) and fails on every strongly-connected component,
printing a witness site for each edge of one representative cycle.

**blocking-under-lock** flags calls from the configurable
``BLOCKING_CALLS`` set made (directly or through resolved callees)
while any lock is held. ``Condition.wait`` on the *only* held lock is
exempt (wait releases it); audited exceptions live in
``analysis/allowlist.py`` with one-line justifications.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from analysis import core
from analysis.allowlist import BLOCKING_ALLOWLIST

LOCK_ORDER = "lock-order"
BLOCKING = "blocking-under-lock"

# ------------------------------------------------------- blocking config

#: dotted callee names that always block (module-qualified spellings)
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "os.replace": "file I/O",
    "os.fsync": "file I/O",
    "rpc.call": "intra-cluster RPC",
    "rpc.call_json": "intra-cluster RPC",
    "rpc.pull_pages": "intra-cluster RPC",
    "jax.device_get": "device DMA",
}

#: terminal (last-component) callee names that always block
BLOCKING_TERMINAL = {
    "urlopen": "raw HTTP",
    "device_put": "device DMA",
    "device_get": "device DMA",
    "page_to_host": "device->host DMA",
    "host_to_page": "host->device DMA",
    "stage_split": "staging DMA + connector read",
    "stage_sharded": "staging DMA",
    "block_until_ready": "device sync",
    "call_json": "intra-cluster RPC",
    "pull_pages": "intra-cluster RPC",
    "record_submit": "journal write",
    "record_finish": "journal write",
    "record_prepare": "journal write",
    "record_deallocate": "journal write",
    "record_kill": "journal write",
}

#: bare names (no attribute) that block — the builtin open
BLOCKING_BARE = {
    "open": "file I/O",
    "sleep": "time.sleep",
    "urlopen": "raw HTTP",
}

#: spool write/read API: blocking when called on a spool-named receiver
SPOOL_METHODS = {"append", "commit", "discard", "serve", "gc"}

#: common container/stdlib verbs excluded from the unique-method
#: call-resolution fallback (list.append must never bind to
#: ExchangeSpool.append just because the spool defines the only
#: ``append`` in the tree)
_METHOD_DENYLIST = {
    "append", "add", "get", "put", "pop", "update", "items", "keys",
    "values", "join", "close", "open", "read", "write", "run", "start",
    "stop", "send", "result", "done", "set", "clear", "copy", "count",
    "index", "remove", "insert", "extend", "split", "strip", "encode",
    "decode", "flush", "acquire", "release", "wait", "notify",
    "notify_all", "submit", "shutdown", "commit", "rollback", "cursor",
    "execute", "fetchone", "fetchall", "time", "total", "stats", "name",
    "sort", "discard", "serve", "gc", "main", "scan",
}

_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}


# ------------------------------------------------------------ lock model


@dataclasses.dataclass(frozen=True)
class LockDef:
    ident: str
    kind: str  # Lock | RLock | Condition
    rel: str
    line: int


@dataclasses.dataclass(frozen=True)
class HeldLock:
    """A lock on the held stack. ``ident`` is None for ambiguous
    attribute locks (held for blocking purposes, no graph edges)."""

    ident: Optional[str]
    attr: str
    line: int

    def label(self) -> str:
        return self.ident or f"?.{self.attr}"


def _mod_ident(rel: str) -> str:
    return rel[:-3] if rel.endswith(".py") else rel


def _ctor_kind(node: ast.AST) -> Optional[Tuple[str, Optional[ast.AST]]]:
    """(kind, condition-wrapped-lock-expr) when ``node`` constructs a
    lock, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = core.call_name(node)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last in _LOCK_CTORS and (
        name == last or name.startswith("threading.")
    ):
        wrapped = None
        if last == "Condition" and node.args:
            wrapped = node.args[0]
        return _LOCK_CTORS[last], wrapped
    # dataclasses.field(default_factory=threading.Lock)
    if last == "field":
        for kw in node.keywords:
            if kw.arg == "default_factory":
                fac = core.dotted_name(kw.value)
                if fac:
                    fl = fac.rsplit(".", 1)[-1]
                    if fl in _LOCK_CTORS and (
                        fac == fl or fac.startswith("threading.")
                    ):
                        return _LOCK_CTORS[fl], None
    return None


class LockRegistry:
    def __init__(self):
        #: attr name -> [(rel, class, LockDef)]
        self.attr_defs: Dict[str, List[Tuple[str, str, LockDef]]] = {}
        #: (rel, class) -> {attr: LockDef}
        self.class_attrs: Dict[Tuple[str, str], Dict[str, LockDef]] = {}
        #: (rel, name) -> LockDef   (module-level)
        self.module_names: Dict[Tuple[str, str], LockDef] = {}
        #: (rel, funcqual, name) -> LockDef   (function-local)
        self.local_names: Dict[Tuple[str, str, str], LockDef] = {}
        #: Condition ident -> underlying lock ident
        self.alias: Dict[str, str] = {}

    def canon(self, ident: str) -> str:
        seen = set()
        while ident in self.alias and ident not in seen:
            seen.add(ident)
            ident = self.alias[ident]
        return ident

    def add_attr(self, rel: str, cls: str, attr: str, kind: str, line: int):
        ident = f"{_mod_ident(rel)}.{cls}.{attr}"
        d = LockDef(ident, kind, rel, line)
        self.attr_defs.setdefault(attr, []).append((rel, cls, d))
        self.class_attrs.setdefault((rel, cls), {})[attr] = d
        return d

    def add_module(self, rel: str, name: str, kind: str, line: int):
        d = LockDef(f"{_mod_ident(rel)}.{name}", kind, rel, line)
        self.module_names[(rel, name)] = d
        return d

    def add_local(self, rel: str, funcqual: str, name: str, kind: str,
                  line: int):
        d = LockDef(
            f"{_mod_ident(rel)}.{funcqual}.{name}", kind, rel, line
        )
        self.local_names[(rel, funcqual, name)] = d
        return d


class _LockCollector(core.ScopedVisitor):
    """First pass over one module: find every lock creation site."""

    def __init__(self, mod: core.Module, reg: LockRegistry):
        super().__init__()
        self.mod = mod
        self.reg = reg

    def _alias_target(self, wrapped: ast.AST) -> Optional[str]:
        """Identity of the lock a Condition wraps, when resolvable."""
        if (
            isinstance(wrapped, ast.Attribute)
            and isinstance(wrapped.value, ast.Name)
            and wrapped.value.id == "self"
            and self.current_class
        ):
            return (
                f"{_mod_ident(self.mod.rel)}."
                f"{self.current_class}.{wrapped.attr}"
            )
        if isinstance(wrapped, ast.Name):
            d = self.reg.module_names.get((self.mod.rel, wrapped.id))
            if d:
                return d.ident
        return None

    def _record(self, target: ast.AST, kind: str, wrapped, line: int):
        d = None
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self.current_class
        ):
            d = self.reg.add_attr(
                self.mod.rel, self.current_class, target.attr, kind, line
            )
        elif isinstance(target, ast.Name):
            if self.func_stack:
                d = self.reg.add_local(
                    self.mod.rel, self.qualname(), target.id, kind, line
                )
            elif self.current_class:
                d = self.reg.add_attr(
                    self.mod.rel, self.current_class, target.id, kind,
                    line,
                )
            else:
                d = self.reg.add_module(
                    self.mod.rel, target.id, kind, line
                )
        if d is not None and wrapped is not None:
            tgt = self._alias_target(wrapped)
            if tgt:
                self.reg.alias[d.ident] = tgt

    def visit_Assign(self, node: ast.Assign):
        got = _ctor_kind(node.value)
        if got:
            kind, wrapped = got
            for t in node.targets:
                self._record(t, kind, wrapped, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            got = _ctor_kind(node.value)
            if got:
                kind, wrapped = got
                self._record(node.target, kind, wrapped, node.lineno)
        self.generic_visit(node)


# -------------------------------------------------------- function model


@dataclasses.dataclass
class FuncInfo:
    fq: str  # "server/worker.py::Worker._execute"
    rel: str
    qual: str
    cls: Optional[str]
    node: ast.AST
    #: direct lock acquisitions: (ident, line)
    acquires: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list
    )
    #: resolved call sites: (callee fq, line, held snapshot)
    calls: List[Tuple[str, int, Tuple[HeldLock, ...]]] = (
        dataclasses.field(default_factory=list)
    )
    #: direct blocking events:
    #: (callname, why, line, held snapshot, wait_lock_ident)
    blocking: List[
        Tuple[str, str, int, Tuple[HeldLock, ...], Optional[str]]
    ] = dataclasses.field(default_factory=list)
    #: direct nesting edges: (held ident, acquired ident, line)
    edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )


class _FuncCollector(core.ScopedVisitor):
    """Enumerate every function (incl. nested) of one module."""

    def __init__(self, mod: core.Module, out: Dict[str, FuncInfo]):
        super().__init__()
        self.mod = mod
        self.out = out

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        qual = self.qualname()
        fq = f"{self.mod.rel}::{qual}"
        self.out[fq] = FuncInfo(
            fq=fq,
            rel=self.mod.rel,
            qual=qual,
            cls=self.current_class,
            node=node,
        )
        self.generic_visit(node)
        self.func_stack.pop()


def _import_aliases(mod: core.Module, pkg: str):
    """(module aliases, function aliases) for presto_tpu-internal
    imports. ``pkg`` is the analyzed package name (src_dir basename).
    Returns name -> module rel  /  name -> (module rel, func name)."""
    mod_alias: Dict[str, str] = {}
    func_alias: Dict[str, Tuple[str, str]] = {}

    def to_rel(dotted: str) -> Optional[str]:
        parts = dotted.split(".")
        if parts[0] == pkg:
            parts = parts[1:]
        elif parts[0] == "presto_tpu":
            parts = parts[1:]
        else:
            return None
        if not parts:
            return None
        return "/".join(parts) + ".py"

    pkg_dir = "/".join(mod.rel.split("/")[:-1])
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                rel = to_rel(a.name)
                if rel:
                    mod_alias[a.asname or a.name.rsplit(".", 1)[-1]] = rel
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_dir
                for _ in range(node.level - 1):
                    base = "/".join(base.split("/")[:-1])
                dotted_base = base.replace("/", ".")
                dotted = (
                    f"{pkg}.{dotted_base}.{node.module}"
                    if node.module and dotted_base
                    else f"{pkg}.{node.module or dotted_base}"
                ).rstrip(".")
            else:
                dotted = node.module or ""
            base_rel = to_rel(dotted) if dotted else None
            for a in node.names:
                name = a.asname or a.name
                # ``from presto_tpu.exec import staging`` -> module
                sub = to_rel(f"{dotted}.{a.name}") if dotted else None
                if sub:
                    mod_alias[name] = sub
                if base_rel:
                    func_alias[name] = (base_rel, a.name)
    # a name that is really a submodule wins over the func form
    for k in mod_alias:
        func_alias.pop(k, None)
    return mod_alias, func_alias


class _Model:
    """The shared concurrency model for one analysis run."""

    def __init__(self, modules: List[core.Module], src_dir: str):
        import os

        self.modules = modules
        self.pkg = os.path.basename(os.path.abspath(src_dir))
        self.reg = LockRegistry()
        self.funcs: Dict[str, FuncInfo] = {}
        self.by_mod: Dict[str, core.Module] = {m.rel: m for m in modules}
        for m in modules:
            _LockCollector(m, self.reg).visit(m.tree)
        for m in modules:
            _FuncCollector(m, self.funcs).visit(m.tree)
        #: method name -> [fq] (class methods only, for the
        #: unique-definition fallback)
        self.methods: Dict[str, List[str]] = {}
        for fq, fi in self.funcs.items():
            if fi.cls:
                self.methods.setdefault(
                    fi.qual.rsplit(".", 1)[-1], []
                ).append(fq)
        #: per-module top-level function index: (rel, name) -> fq
        self.top_funcs: Dict[Tuple[str, str], str] = {}
        for fq, fi in self.funcs.items():
            if "." not in fi.qual:
                self.top_funcs[(fi.rel, fi.qual)] = fq
        self.imports = {
            m.rel: _import_aliases(m, self.pkg) for m in modules
        }
        for fi in self.funcs.values():
            _FuncWalk(self, fi).run()
        self._may_acquire: Dict[str, Dict[str, List[Tuple[str, int]]]] = {}
        self._may_block: Dict[
            str, Dict[str, Tuple[str, int, List[Tuple[str, int]]]]
        ] = {}

    # ------------------------------------------------ lock resolution

    def resolve_lock(
        self, expr: ast.AST, rel: str, cls: Optional[str], qual: str
    ) -> Optional[HeldLock]:
        """HeldLock for a with-expression / acquire receiver, or None
        when the expression is not a known lock."""
        line = getattr(expr, "lineno", 0)
        if isinstance(expr, ast.Name):
            # lexical lookup: innermost enclosing function first
            parts = qual.split(".")
            for i in range(len(parts), 0, -1):
                d = self.reg.local_names.get(
                    (rel, ".".join(parts[:i]), expr.id)
                )
                if d:
                    return HeldLock(
                        self.reg.canon(d.ident), expr.id, line
                    )
            d = self.reg.module_names.get((rel, expr.id))
            if d:
                return HeldLock(self.reg.canon(d.ident), expr.id, line)
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        defs = self.reg.attr_defs.get(attr)
        if not defs:
            return None
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            if cls:
                own = self.reg.class_attrs.get((rel, cls), {}).get(attr)
                if own:
                    return HeldLock(
                        self.reg.canon(own.ident), attr, line
                    )
        if len(defs) == 1:
            return HeldLock(
                self.reg.canon(defs[0][2].ident), attr, line
            )
        # receiver-name hint: `arbiter._lock` -> ClusterMemoryArbiter
        recv = core.terminal_name(expr.value)
        if recv:
            r = recv.lower().lstrip("_")
            hits = [
                d
                for (_rel, c, d) in defs
                if c.lower().startswith(r) or r in c.lower()
            ]
            if len(hits) == 1:
                return HeldLock(self.reg.canon(hits[0].ident), attr, line)
        return HeldLock(None, attr, line)  # known lock attr, ambiguous

    # ------------------------------------------------ call resolution

    def resolve_call(
        self, call: ast.Call, rel: str, cls: Optional[str]
    ) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            fq = self.top_funcs.get((rel, func.id))
            if fq:
                return fq
            _mods, funcs = self.imports.get(rel, ({}, {}))
            tgt = funcs.get(func.id)
            if tgt:
                return self.top_funcs.get(tgt)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        recv = func.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls:
                fq = f"{rel}::{cls}.{meth}"
                if fq in self.funcs:
                    return fq
            # Class.method in the same module
            fq = f"{rel}::{recv.id}.{meth}"
            if fq in self.funcs:
                return fq
            # imported presto_tpu module: rpc.call_json(...)
            mods, _funcs = self.imports.get(rel, ({}, {}))
            target_rel = mods.get(recv.id)
            if target_rel:
                return self.top_funcs.get((target_rel, meth)) or (
                    None
                )
        # globally-unique method name (common verbs excluded); when
        # several classes define it, a receiver-name hint may still
        # pin one (`self.pool.reserve` -> MemoryPool.reserve)
        if meth not in _METHOD_DENYLIST:
            cands = self.methods.get(meth, ())
            if len(cands) == 1:
                return cands[0]
            if len(cands) > 1:
                recv = core.terminal_name(func.value)
                if recv:
                    r = recv.lower().lstrip("_")
                    hits = [
                        fq
                        for fq in cands
                        if r
                        and r in self.funcs[fq].qual.split(".")[0].lower()
                    ]
                    if len(hits) == 1:
                        return hits[0]
        return None

    # -------------------------------------------------- summaries

    def may_acquire(
        self, fq: str, _stack: Optional[Set[str]] = None
    ) -> Dict[str, List[Tuple[str, int]]]:
        """ident -> call chain [(fq, line), ...] ending at the
        acquisition site, through resolved calls (fixpoint)."""
        if fq in self._may_acquire:
            return self._may_acquire[fq]
        stack = _stack if _stack is not None else set()
        if fq in stack:
            return {}
        stack.add(fq)
        fi = self.funcs.get(fq)
        out: Dict[str, List[Tuple[str, int]]] = {}
        if fi is not None:
            for ident, line in fi.acquires:
                out.setdefault(ident, [(fq, line)])
            for callee, line, _held in fi.calls:
                for ident, chain in self.may_acquire(
                    callee, stack
                ).items():
                    out.setdefault(ident, [(fq, line)] + chain)
        stack.discard(fq)
        # memoized even when computed under a recursion cut: the cut
        # under-approximates propagation THROUGH a call cycle, which
        # is acceptable (and keeps the fixpoint linear)
        self._may_acquire[fq] = out
        return out

    def may_block(
        self, fq: str, _stack: Optional[Set[str]] = None
    ) -> Dict[str, Tuple[str, int, List[Tuple[str, int]], Optional[str]]]:
        """blocking call name ->
        (why, line, chain [(fq, line), ...], wait_lock_ident).

        Condition-waits propagate WITH the identity of the lock the
        wait releases: whether they block a caller depends on the
        caller's held set (holding only that same lock is fine — wait
        releases it; holding anything else wedges that lock for the
        whole wait)."""
        if fq in self._may_block:
            return self._may_block[fq]
        stack = _stack if _stack is not None else set()
        if fq in stack:
            return {}
        stack.add(fq)
        fi = self.funcs.get(fq)
        out: Dict[
            str, Tuple[str, int, List[Tuple[str, int]], Optional[str]]
        ] = {}
        if fi is not None:
            for name, why, line, _held, wait_ident in fi.blocking:
                out.setdefault(
                    name, (why, line, [(fq, line)], wait_ident)
                )
            for callee, line, _held in fi.calls:
                for name, (why, bline, chain, wid) in self.may_block(
                    callee, stack
                ).items():
                    out.setdefault(
                        name, (why, bline, [(fq, line)] + chain, wid)
                    )
        stack.discard(fq)
        self._may_block[fq] = out  # see may_acquire on recursion cuts
        return out


class _FuncWalk:
    """Held-set walk of one function body: fills FuncInfo events."""

    def __init__(self, model: _Model, fi: FuncInfo):
        self.model = model
        self.fi = fi
        self.held: List[HeldLock] = []

    def run(self):
        for stmt in self.fi.node.body:
            self._visit(stmt)

    # ---- traversal

    def _visit(self, node: ast.AST):
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Lambda),
        ):
            return  # separate execution context (walked as its own unit)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node)
            return
        if isinstance(node, ast.Call):
            self._call(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _with(self, node):
        pushed = 0
        for item in node.items:
            # the context expression evaluates BEFORE acquisition
            self._visit(item.context_expr)
            ref = self.model.resolve_lock(
                item.context_expr, self.fi.rel, self.fi.cls, self.fi.qual
            )
            if ref is not None:
                self._acquire(ref)
                self.held.append(ref)
                pushed += 1
        for stmt in node.body:
            self._visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    # ---- events

    def _acquire(self, ref: HeldLock):
        if ref.ident is not None:
            self.fi.acquires.append((ref.ident, ref.line))
            for h in self.held:
                if h.ident is not None and h.ident != ref.ident:
                    self.fi.edges.append((h.ident, ref.ident, ref.line))

    def _held_snapshot(self) -> Tuple[HeldLock, ...]:
        return tuple(self.held)

    def _call(self, call: ast.Call):
        name = core.call_name(call)
        term = core.terminal_name(call.func)
        line = call.lineno
        # explicit .acquire() on a known lock
        if term == "acquire" and isinstance(call.func, ast.Attribute):
            ref = self.model.resolve_lock(
                call.func.value, self.fi.rel, self.fi.cls, self.fi.qual
            )
            if ref is not None:
                self._acquire(
                    HeldLock(ref.ident, ref.attr, line)
                )
                return
        # Condition.wait while holding OTHER locks
        if term in ("wait", "wait_for") and isinstance(
            call.func, ast.Attribute
        ):
            ref = self.model.resolve_lock(
                call.func.value, self.fi.rel, self.fi.cls, self.fi.qual
            )
            if ref is not None:
                self.fi.blocking.append(
                    (
                        f"{ref.label()}.{term}",
                        "condition wait",
                        line,
                        self._held_snapshot(),
                        ref.ident or f"?.{ref.attr}",
                    )
                )
                return
        why = self._blocking_why(call, name, term)
        if why is not None:
            self.fi.blocking.append(
                (
                    name or term or "<call>",
                    why,
                    line,
                    self._held_snapshot(),
                    None,
                )
            )
        callee = self.model.resolve_call(call, self.fi.rel, self.fi.cls)
        if callee is not None:
            self.fi.calls.append((callee, line, self._held_snapshot()))

    def _blocking_why(
        self, call: ast.Call, name: Optional[str], term: Optional[str]
    ) -> Optional[str]:
        if name in BLOCKING_DOTTED:
            return BLOCKING_DOTTED[name]
        if isinstance(call.func, ast.Name):
            if call.func.id in BLOCKING_BARE:
                return BLOCKING_BARE[call.func.id]
            # imported-from spellings: `from ..staging import
            # page_to_host; page_to_host(x)`
            return BLOCKING_TERMINAL.get(call.func.id)
        if not isinstance(call.func, ast.Attribute):
            return None
        if term in BLOCKING_TERMINAL:
            return BLOCKING_TERMINAL[term]
        # unbounded thread join: zero-argument .join() (str.join
        # always takes the iterable argument)
        if term == "join" and not call.args and not call.keywords:
            return "unbounded thread join"
        # spool writes: spool-named receiver
        if term in SPOOL_METHODS:
            recv = core.terminal_name(call.func.value)
            if recv and "spool" in recv.lower():
                return "spool I/O"
        return None


# --------------------------------------------------------------- passes

#: size-1 model cache: both concurrency passes run over the SAME
#: loaded module list within one run_passes() call — build the model
#: once. Keyed by CONTENT (per-module source hashes), never object
#: identity: a second run over re-parsed (possibly edited) sources
#: must rebuild, and CPython recycles list ids across runs.
_MODEL_CACHE: dict = {}


def _model_for(modules, src_dir) -> _Model:
    key = (
        src_dir,
        tuple((m.rel, hash(m.source)) for m in modules),
    )
    if _MODEL_CACHE.get("key") != key:
        _MODEL_CACHE["key"] = key
        _MODEL_CACHE["model"] = _Model(modules, src_dir)
    return _MODEL_CACHE["model"]


def _fmt_held(held: Tuple[HeldLock, ...]) -> str:
    return ", ".join(h.label() for h in held)


def _fmt_chain(chain: List[Tuple[str, int]]) -> str:
    hops = [
        f"{fq.split('::', 1)[1]} (line {line})" for fq, line in chain
    ]
    return " -> ".join(hops[:4])


@core.register(
    LOCK_ORDER,
    "static deadlock detection: the held-while-acquiring lock graph "
    "must stay acyclic",
)
def lock_order_pass(modules, src_dir):
    model = _model_for(modules, src_dir)
    # edge (A, B) -> witness (rel, line, funcqual, description)
    edges: Dict[Tuple[str, str], Tuple[str, int, str, str]] = {}
    for fi in model.funcs.values():
        for a, b, line in fi.edges:
            edges.setdefault(
                (a, b), (fi.rel, line, fi.qual, "nested acquisition")
            )
        for callee, line, held in fi.calls:
            if not held:
                continue
            for ident, chain in model.may_acquire(callee).items():
                for h in held:
                    if h.ident is None or h.ident == ident:
                        continue
                    edges.setdefault(
                        (h.ident, ident),
                        (
                            fi.rel,
                            line,
                            fi.qual,
                            f"via call {_fmt_chain(chain)}",
                        ),
                    )
    findings = []
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    for cycle in _cycles(adj):
        steps = []
        anchor = None
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            rel, line, qual, how = edges[(a, b)]
            if anchor is None:
                anchor = (rel, line)
            steps.append(
                f"{a} -> {b} [{rel}:{line} in {qual}, {how}]"
            )
        rel, line = anchor
        mod = next(m for m in modules if m.rel == rel)
        findings.append(
            mod.finding(
                LOCK_ORDER,
                line,
                "lock-order cycle (potential deadlock): "
                + "; ".join(steps),
            )
        )
    return findings


def _cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """One representative cycle per strongly-connected component
    (Tarjan), deterministic order."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strong(v: str):
        # iterative Tarjan (analysis trees can be deep)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or (
                    node in adj.get(node, ())
                ):
                    sccs.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strong(v)
    # extract one concrete cycle per SCC by DFS inside the component
    cycles = []
    for comp in sccs:
        compset = set(comp)
        start = comp[0]
        path = [start]
        seen = {start}

        def dfs(v) -> Optional[List[str]]:
            for w in sorted(adj.get(v, ())):
                if w == start and len(path) > 0:
                    return list(path)
                if w in compset and w not in seen:
                    seen.add(w)
                    path.append(w)
                    got = dfs(w)
                    if got:
                        return got
                    path.pop()
            return None

        got = dfs(start)
        if got:
            cycles.append(got)
    return cycles


@core.register(
    BLOCKING,
    "no blocking call (RPC, DMA, file I/O, sleep, unbounded join, "
    "journal/spool writes) while a lock is held",
)
def blocking_under_lock_pass(modules, src_dir):
    model = _model_for(modules, src_dir)
    findings = []
    seen: Set[Tuple[str, int, str]] = set()

    def emit(fi: FuncInfo, line: int, callname: str, why: str,
             held, chain_desc: str = ""):
        key = (fi.rel, line, callname)
        if key in seen:
            return
        seen.add(key)
        mod = model.by_mod[fi.rel]
        msg = (
            f"blocking call {callname} ({why}) while holding "
            f"{_fmt_held(held)} in {fi.qual}"
        )
        if chain_desc:
            msg += f" [{chain_desc}]"
        f = mod.finding(BLOCKING, line, msg)
        for entry in BLOCKING_ALLOWLIST:
            if (
                entry.path == fi.rel
                and entry.func == fi.qual
                and entry.call == callname
            ):
                f.allowlisted = True
                f.justification = entry.why
                break
        findings.append(f)

    for fi in model.funcs.values():
        for name, why, line, held, wait_ident in fi.blocking:
            if not held:
                continue
            if wait_ident is not None:
                # Condition.wait releases ITS OWN lock; flag only when
                # other locks stay held across the wait
                others = [
                    h for h in held if h.label() != wait_ident
                ]
                if others:
                    emit(
                        fi, line, name,
                        "condition wait holding unrelated lock(s)",
                        tuple(others),
                    )
                continue
            emit(fi, line, name, why, held)
        for callee, line, held in fi.calls:
            if not held:
                continue
            for name, (why, _bline, chain, wid) in model.may_block(
                callee
            ).items():
                if wid is not None:
                    others = tuple(
                        h for h in held if h.label() != wid
                    )
                    if not others:
                        continue
                    emit(
                        fi, line, name,
                        "condition wait holding unrelated lock(s)",
                        others,
                        chain_desc=f"via {_fmt_chain(chain)}",
                    )
                    continue
                emit(
                    fi, line, name, why, held,
                    chain_desc=f"via {_fmt_chain(chain)}",
                )
    return findings
