"""Audited exceptions for the ``blocking-under-lock`` pass.

Every entry is a deliberate, reviewed decision to hold a lock across a
blocking call, with a one-line justification. Adding an entry is a
code-review event: the justification must say why the blocking work
cannot move outside the critical section (or why the lock is private
to exactly that work). Prefer restructuring (copy outside the lock,
snapshot-then-release) — the split-cache spill path and the arbiter
kill path were both restructured rather than allowlisted.

Match shape: (path relative to the analyzed root, enclosing function
qualname, blocking callee name as reported by the pass).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Allow:
    path: str  #: module rel path, e.g. "server/journal.py"
    func: str  #: enclosing function qualname, e.g. "Journal._append"
    call: str  #: blocking callee as reported, e.g. "open"
    why: str  #: one-line justification (this IS the audit record)


BLOCKING_ALLOWLIST = [
    Allow(
        "server/journal.py",
        "CoordinatorJournal._append",
        "open",
        "the journal lock exists to serialize exactly this append: "
        "on-disk frame order must equal in-memory apply order or "
        "replay diverges (submit-before-finish), and rotation + "
        "checkpoint must be atomic against concurrent appends",
    ),
    Allow(
        "plan/history.py",
        "QueryHistoryStore.record_query",
        "open",
        "segment append + rotation + checkpoint snapshot must be "
        "atomic against concurrent records or GC could drop the only "
        "on-disk copy of live entries (same invariant as the "
        "coordinator journal); the store lock guards exactly this",
    ),
    Allow(
        "exec/stats.py",
        "JsonlQueryEventListener.query_completed",
        "open",
        "the listener lock exists to serialize exactly this append: "
        "concurrent query completions must not interleave partial "
        "JSONL lines (consumers tail this file)",
    ),
    Allow(
        "exec/stats.py",
        "SlowQueryLog.query_completed",
        "open",
        "the log lock exists to serialize exactly this append: a "
        "multi-line EXPLAIN ANALYZE block must land contiguously or "
        "the log is unreadable",
    ),
    Allow(
        "native.py",
        "_load",
        "os.replace",
        "one-time lazy native build: the module lock guarantees a "
        "single compiler invocation + atomic .so swap; every later "
        "call takes the fast already-loaded path",
    ),
    Allow(
        "native.py",
        "_load_gen",
        "os.replace",
        "one-time lazy native build (generator twin of _load): single "
        "compiler invocation + atomic .so swap under the module lock",
    ),
    Allow(
        "server/spool.py",
        "ExchangeSpool.commit",
        "open",
        "the commit-marker write must serialize with GC's "
        "marker-first removal under the same lock — commit-vs-GC "
        "ordering is the recovery correctness invariant (a marker "
        "written after GC unlinked the pages would resurrect a "
        "half-deleted attempt)",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.append",
        "open",
        "the lane lock exists to serialize exactly this append: "
        "on-disk batch-frame order must equal seq order or replay "
        "re-admits the wrong tail (same invariant as the coordinator "
        "journal)",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager._flush_lane",
        "open",
        "the commit frame is the durability point AND the snapshot-id "
        "mint: it must land strictly after every batch frame it "
        "covers and strictly ordered against concurrent appends — "
        "the lane lock guards exactly that ordering",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.record_mview",
        "open",
        "the mview-definition log lock exists to serialize exactly "
        "this append: interleaved create/drop frames would replay "
        "into the wrong live-view set",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.record_mview_drop",
        "open",
        "drop frames serialize against create frames under the same "
        "log lock (see record_mview); replay order is the live-view "
        "set",
    ),
    Allow(
        "server/spool.py",
        "ExchangeSpool._read_frames",
        "open",
        "recovery reads hold the lock so GC cannot unlink the pages "
        "file mid-read; recovery is rare and the frames are small "
        "(the hot exchange path never touches the spool reader)",
    ),
    Allow(
        "server/journal.py",
        "CoordinatorJournal._append",
        "os.fsync",
        "durable-before-acknowledged: the claim/admission frame must "
        "reach stable storage inside the same critical section that "
        "ordered it — fsync after releasing would let a later frame's "
        "sync overtake an earlier unsynced one (see the open entry)",
    ),
    Allow(
        "server/spool.py",
        "ExchangeSpool.commit",
        "os.fsync",
        "the marker fsync rides the same commit-vs-GC critical "
        "section as its write (see the open entry): a synced marker "
        "over pages GC already unlinked is the half-commit the "
        "ordering exists to prevent",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.append",
        "os.fsync",
        "durable-before-acknowledged: the batch frame is acked to "
        "the producer when append returns, so the sync must complete "
        "under the same lane lock that fixed its on-disk order",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager._flush_lane",
        "os.fsync",
        "the commit frame's sync is the durability point of the "
        "snapshot id it mints — it cannot move outside the lane lock "
        "without letting a concurrent append reorder against it",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.record_mview",
        "os.fsync",
        "definition frames are acked-durable like data frames; the "
        "sync shares the log lock that orders create against drop",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.record_mview_drop",
        "os.fsync",
        "drop frames sync under the same log lock as create frames "
        "(see record_mview)",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.compaction_tick",
        "open",
        "_commit_mu is held across the whole compaction publish BY "
        "DESIGN: compaction must not race an ingest commit minting "
        "the same snapshot id, and the background lane only runs "
        "when the QoS plane reports the cluster idle",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.compaction_tick",
        "os.fsync",
        "the compaction publish (data files, manifest, pointer) "
        "syncs under _commit_mu — same reasoning as its open entry",
    ),
    Allow(
        "server/ingest.py",
        "IngestManager.compaction_tick",
        "os.replace",
        "the compaction pointer swap is atomic-rename under "
        "_commit_mu — same reasoning as its open entry",
    ),
    Allow(
        "server/worker.py",
        "WorkerServer._materialize_ici",
        "jax.device_get",
        "the materialize latch exists to serialize exactly this "
        "degrade: result pulls of an ICI task must block until its "
        "serialized buffers are COMPLETE (a half-materialized buffer "
        "under X-Complete is silent data loss), and the latch is "
        "taken by nothing else — drain and the results handler are "
        "its only users, off the produce/consume hot path",
    ),
    Allow(
        "server/worker.py",
        "WorkerServer._materialize_ici",
        "utils/memory.MemoryPool._lock.wait",
        "the governance-lane reserve for materialized frames may "
        "block for headroom while the materialize latch is held; the "
        "latch is private to this degrade (see the device_get entry) "
        "and blocking pullers behind an under-pressure materialize is "
        "the intended backpressure",
    ),
]
