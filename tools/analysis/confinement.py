"""Module-confinement passes: privileged constructs stay inside their
one audited module (plus explicitly audited consumers).

These are the AST migrations of the legacy regex lints
(``tools/check_*.py`` — the CLIs survive as shims over these passes):

- ``rpc-confinement``       raw ``urlopen`` outside server/rpc.py
- ``staging-confinement``   ``device_put`` anywhere / ``jnp.asarray``
                            at host-boundary layers, outside
                            exec/staging.py
- ``dynfilter-confinement`` filter-summary construction outside
                            exec/dynfilter.py
- ``attempt-ids``           task-id f-strings / string-parsing outside
                            server/task_ids.py
- ``journal-sites``         journal frames outside server/journal.py;
                            record/replay outside journal+coordinator
                            (+ memory_arbiter for kill frames)
- ``reserve-sites``         pool construction / reservations outside
                            utils/memory.py + audited consumers

Being AST-level, they see calls (not lines): comments, docstrings,
``isinstance`` checks and attribute reads no longer need scrub
patterns, and a disallowed call on a line that also carries an exempt
read still flags.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from analysis import core


def _walk_calls(mod: core.Module):
    for node in mod.nodes:
        if isinstance(node, ast.Call):
            yield node


def _receiver(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return core.terminal_name(call.func.value)
    return None


# ---------------------------------------------------------------- rpc


@core.register(
    "rpc-confinement",
    "every intra-cluster HTTP call goes through server/rpc.py "
    "(timeouts, retries, breakers, fault hooks, rpc.* metrics)",
)
def rpc_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        if mod.rel == "server/rpc.py":
            continue
        for call in _walk_calls(mod):
            if core.terminal_name(call.func) == "urlopen":
                findings.append(
                    mod.finding(
                        "rpc-confinement",
                        call.lineno,
                        "raw urlopen — route through "
                        "presto_tpu.server.rpc (config-driven "
                        "timeouts, retries, circuit breakers)",
                    )
                )
    return findings


# ------------------------------------------------------------- staging

_HOST_BOUNDARY = ("server", "connectors", "parallel")

#: audited device-boundary modules beside exec/staging.py: the
#: exchange plane's kernels (parallel/exchange.py) and their SPI
#: orchestration (server/exchange_spi.py) move hash/remap tables and
#: traced scalars to device as kernel parameters — not page staging;
#: the pages they build are accounted by the worker under the same
#: owners the staged path uses, and the exchange-plane rule confines
#: the constructs themselves
_STAGING_EXEMPT = {
    "exec/staging.py",
    "parallel/exchange.py",
    "server/exchange_spi.py",
}


@core.register(
    "staging-confinement",
    "host->device transfers go through exec/staging.py (split cache, "
    "memory accounting, staging.* metrics)",
)
def staging_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        if mod.rel in _STAGING_EXEMPT:
            continue
        top = mod.rel.split("/")[0]
        boundary = top in _HOST_BOUNDARY
        for call in _walk_calls(mod):
            term = core.terminal_name(call.func)
            if term == "device_put":
                findings.append(
                    mod.finding(
                        "staging-confinement",
                        call.lineno,
                        "raw device_put — stage through "
                        "presto_tpu.exec.staging instead",
                    )
                )
            elif boundary and isinstance(call.func, ast.Attribute):
                name = core.call_name(call)
                if name in ("jnp.asarray", "jnp.array"):
                    findings.append(
                        mod.finding(
                            "staging-confinement",
                            call.lineno,
                            f"{name} at a host-boundary layer is a "
                            "staging act — route through "
                            "presto_tpu.exec.staging",
                        )
                    )
    return findings


# ----------------------------------------------------------- dynfilter


@core.register(
    "dynfilter-confinement",
    "build-side filter summaries are constructed only in "
    "exec/dynfilter.py (native-dtype bounds, NDV caps, merge/wire)",
)
def dynfilter_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        if mod.rel == "exec/dynfilter.py":
            continue
        for call in _walk_calls(mod):
            term = core.terminal_name(call.func)
            msg = None
            if term in ("ColumnFilter", "FilterSummary"):
                msg = f"ad-hoc {term} construction"
            elif term == "RangeSet" and any(
                kw.arg == "lo" for kw in call.keywords
            ):
                msg = "ad-hoc RangeSet constraint assembly"
            elif (
                core.call_name(call) in ("jnp.min", "jnp.max")
                and call.args
                and isinstance(call.args[0], ast.Call)
                and core.call_name(call.args[0]) == "jnp.where"
            ):
                msg = (
                    "ad-hoc build-side min/max-over-where reduction "
                    "(32-bit truncation hazard)"
                )
            if msg:
                findings.append(
                    mod.finding(
                        "dynfilter-confinement",
                        call.lineno,
                        msg + " — build through "
                        "presto_tpu.exec.dynfilter",
                    )
                )
    return findings


# ---------------------------------------------------------- attempt ids

_TASK_ID_NAMES = {"task_id", "src_task", "tid"}
_SPLIT_METHS = {"split", "rsplit", "partition", "rpartition"}


@core.register(
    "attempt-ids",
    "task/attempt-id construction and parsing confined to "
    "server/task_ids.py (spool dedup correctness)",
)
def attempt_ids_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        if mod.rel == "server/task_ids.py":
            continue
        for node in mod.nodes:
            # task_id = f"..."  (assignment or keyword argument)
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.JoinedStr
            ):
                for t in node.targets:
                    if core.terminal_name(t) == "task_id":
                        findings.append(
                            mod.finding(
                                "attempt-ids",
                                node.lineno,
                                "f-string task id — mint through "
                                "presto_tpu.server.task_ids",
                            )
                        )
            elif isinstance(node, ast.keyword) and (
                node.arg == "task_id"
                and isinstance(node.value, ast.JoinedStr)
            ):
                findings.append(
                    mod.finding(
                        "attempt-ids",
                        node.value.lineno,
                        "f-string task id — mint through "
                        "presto_tpu.server.task_ids",
                    )
                )
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if (
                    node.func.attr in _SPLIT_METHS
                    and core.terminal_name(node.func.value)
                    in _TASK_ID_NAMES
                ):
                    findings.append(
                        mod.finding(
                            "attempt-ids",
                            node.lineno,
                            "string-parsing a task id — parse "
                            "through presto_tpu.server.task_ids",
                        )
                    )
    return findings


# ------------------------------------------------------------- journal

_JOURNAL = "server/journal.py"
_JOURNAL_CONSUMERS = {_JOURNAL, "server/coordinator.py"}
#: kill frames are journaled from the arbiter's decision point
_KILL_CONSUMERS = _JOURNAL_CONSUMERS | {"server/memory_arbiter.py"}
_RECORD_METHS = {
    "record_submit",
    "record_finish",
    "record_prepare",
    "record_deallocate",
    # multi-coordinator failover frames: a claimant stamps the claimed
    # journal and aliases the dead incarnation's qids into its own
    "record_claim",
    "record_alias",
}


@core.register(
    "journal-sites",
    "journal frames confined to server/journal.py; record/replay to "
    "its audited consumers (coordinator; arbiter for kill frames)",
)
def journal_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        frame_ok = mod.rel == _JOURNAL
        for node in mod.nodes:
            if isinstance(node, ast.Call):
                term = core.terminal_name(node.func)
                if not frame_ok and term in (
                    "_frame_line",
                    "_parse_line",
                ):
                    findings.append(
                        mod.finding(
                            "journal-sites",
                            node.lineno,
                            f"journal frame internal {term}() outside "
                            "server/journal.py",
                        )
                    )
                elif (
                    term == "CoordinatorJournal"
                    or term in _RECORD_METHS
                ) and mod.rel not in _JOURNAL_CONSUMERS:
                    findings.append(
                        mod.finding(
                            "journal-sites",
                            node.lineno,
                            f"journal API {term}() outside the "
                            "audited consumers (server/journal.py, "
                            "server/coordinator.py)",
                        )
                    )
                elif (
                    term == "record_kill"
                    and mod.rel not in _KILL_CONSUMERS
                ):
                    findings.append(
                        mod.finding(
                            "journal-sites",
                            node.lineno,
                            "journal API record_kill() outside the "
                            "audited consumers",
                        )
                    )
                elif (
                    term == "replay"
                    and isinstance(node.func, ast.Attribute)
                    and mod.rel not in _JOURNAL_CONSUMERS
                ):
                    findings.append(
                        mod.finding(
                            "journal-sites",
                            node.lineno,
                            ".replay() outside the audited consumers",
                        )
                    )
            elif (
                not frame_ok
                and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith("journal-")
            ):
                findings.append(
                    mod.finding(
                        "journal-sites",
                        node.lineno,
                        "journal segment-name prefix outside "
                        "server/journal.py",
                    )
                )
    return findings


# --------------------------------------------------------- lease plane

_LEASE = "server/lease.py"
#: the lease plane's privileged constructs and their one audited
#: consumer: construction, expiry claims, fencing checks, and renewal
#: all happen from the coordinator's lease loop / failover path. A
#: rogue claim site elsewhere could steal a live journal; a write
#: path that skips check_fence() could double-resume a query after
#: its claim was superseded (split-brain).
_LEASE_CONSUMERS = {_LEASE, "server/coordinator.py"}
_LEASE_METHS = ("LeasePlane", "claim_expired", "check_fence", "renew")


@core.register(
    "lease-plane",
    "lease construction/claims/fencing confined to server/lease.py + "
    "the coordinator (split-brain safety); lease-/claim- file-name "
    "prefixes to server/lease.py",
)
def lease_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        frame_ok = mod.rel == _LEASE
        for node in mod.nodes:
            if isinstance(node, ast.Call):
                term = core.terminal_name(node.func)
                if (
                    term in _LEASE_METHS
                    and mod.rel not in _LEASE_CONSUMERS
                ):
                    findings.append(
                        mod.finding(
                            "lease-plane",
                            node.lineno,
                            f"lease construct {term}() outside the "
                            "audited modules (server/lease.py, "
                            "server/coordinator.py) — route through "
                            "presto_tpu.server.lease",
                        )
                    )
            elif (
                not frame_ok
                and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and (
                    node.value.startswith("lease-")
                    or node.value.startswith("claim-")
                )
            ):
                findings.append(
                    mod.finding(
                        "lease-plane",
                        node.lineno,
                        "lease/claim file-name prefix outside "
                        "server/lease.py — peers must agree on ONE "
                        "on-disk naming scheme",
                    )
                )
    return findings


# -------------------------------------------------------------- ingest

_INGEST = "server/ingest.py"


@core.register(
    "ingest-frames",
    "WAL frame construction/parse and snapshot-id minting confined to "
    "server/ingest.py (replay + snapshot-isolation correctness)",
)
def ingest_pass(modules: List[core.Module], src_dir: str):
    """The streaming-ingest twin of ``journal-sites``: the WAL frame
    helpers (``_wal_frame``/``_parse_wal_line``), the on-disk ``wal-``
    segment-name prefix, and ``commit_snapshot`` — the one call that
    registers a MINTED snapshot id against a connector — stay inside
    server/ingest.py. An ad-hoc frame writer elsewhere would silently
    break replay; a second id minter would let two commit paths hand
    readers conflicting versions."""
    findings = []
    for mod in modules:
        frame_ok = mod.rel == _INGEST
        for node in mod.nodes:
            if isinstance(node, ast.Call):
                term = core.terminal_name(node.func)
                if not frame_ok and term in (
                    "_wal_frame",
                    "_parse_wal_line",
                ):
                    findings.append(
                        mod.finding(
                            "ingest-frames",
                            node.lineno,
                            f"WAL frame internal {term}() outside "
                            "server/ingest.py",
                        )
                    )
                elif (
                    term == "commit_snapshot"
                    and isinstance(node.func, ast.Attribute)
                    and not frame_ok
                ):
                    findings.append(
                        mod.finding(
                            "ingest-frames",
                            node.lineno,
                            "commit_snapshot() outside the ingest "
                            "lane — snapshot ids are minted (and made "
                            "durable) only by server/ingest.py's "
                            "commit frames",
                        )
                    )
            elif (
                not frame_ok
                and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith("wal-")
            ):
                findings.append(
                    mod.finding(
                        "ingest-frames",
                        node.lineno,
                        "ingest WAL segment-name prefix outside "
                        "server/ingest.py",
                    )
                )
    return findings


# ------------------------------------------------------ manifest plane

_MANIFESTS = "server/manifests.py"

#: ManifestStore construction is privileged but has audited consumers:
#: the ingest lane (the one writer) and the lakehouse mixin inside the
#: manifest module itself (file connectors reach it ONLY through
#: ``_init_lakehouse``)
_MANIFEST_STORE_OK = {_MANIFESTS, "server/ingest.py"}


@core.register(
    "manifest-plane",
    "lakehouse manifest frame construction/parse, the _current pointer "
    "swap, and data-file/manifest publication confined to "
    "server/manifests.py (crash-safe commit protocol)",
)
def manifest_pass(modules: List[core.Module], src_dir: str):
    """The durable-lakehouse twin of ``ingest-frames``: the crc32
    frame helpers (``_manifest_frame``/``_parse_manifest_line``), the
    three publication seams (``_write_data_file``/``_write_manifest``/
    ``_swap_current`` — the exact kill-ordering the chaos suite
    certifies), and the on-disk ``_current`` pointer name stay inside
    server/manifests.py. A second pointer-swap site elsewhere could
    publish a manifest whose data files were never fsynced — the
    half-commit the whole format exists to rule out. ManifestStore
    itself constructs only in the audited consumers (the ingest lane;
    connectors go through the mixin)."""
    findings = []
    for mod in modules:
        frame_ok = mod.rel == _MANIFESTS
        for node in mod.nodes:
            if isinstance(node, ast.Call):
                term = core.terminal_name(node.func)
                if not frame_ok and term in (
                    "_manifest_frame",
                    "_parse_manifest_line",
                    "_write_data_file",
                    "_write_manifest",
                    "_swap_current",
                ):
                    findings.append(
                        mod.finding(
                            "manifest-plane",
                            node.lineno,
                            f"manifest-plane internal {term}() outside "
                            "server/manifests.py — the commit protocol "
                            "(fsync ordering, pointer-swap-last) is "
                            "audited in ONE module",
                        )
                    )
                elif (
                    term == "ManifestStore"
                    and mod.rel not in _MANIFEST_STORE_OK
                ):
                    findings.append(
                        mod.finding(
                            "manifest-plane",
                            node.lineno,
                            "ManifestStore() outside the audited "
                            "consumers (server/ingest.py; connectors "
                            "attach via LakehouseConnectorMixin."
                            "_init_lakehouse)",
                        )
                    )
            elif (
                not frame_ok
                and isinstance(node, ast.Constant)
                and node.value == "_current"
            ):
                findings.append(
                    mod.finding(
                        "manifest-plane",
                        node.lineno,
                        "lakehouse _current pointer name outside "
                        "server/manifests.py — readers and the swap "
                        "must agree on ONE on-disk pointer",
                    )
                )
    return findings


# ----------------------------------------------------------- qos plane

_QOS = "server/qos.py"
_QOS_COORD = {_QOS, "server/coordinator.py"}

#: the QoS plane's privileged constructs and their audited callers:
#: the controller + its admission/checkpoint seams are reachable only
#: from the coordinator; the suspend-side-effect hooks — journal
#: frames, arbiter reservation release, spool progress scans — only
#: from server/qos.py (victim selection, suspend, and resume live
#: there as the ONE audited module). A rogue suspend path elsewhere
#: could park a query nothing ever resumes.
_QOS_CALLS = {
    "QosController": _QOS_COORD,
    "qos_admit": _QOS_COORD,
    "qos_release": _QOS_COORD,
    "qos_checkpoint": _QOS_COORD,
    "speculation_scale": _QOS_COORD,
    "record_suspend": {"server/journal.py", _QOS},
    "record_resume": {"server/journal.py", _QOS},
    "suspend_release": {"server/memory_arbiter.py", _QOS},
    "committed_for_query": {"server/spool.py", _QOS},
}


@core.register(
    "qos-plane",
    "QoS suspend/resume/victim-selection constructs confined to "
    "server/qos.py + audited consumers (coordinator admission seam; "
    "journal/arbiter/spool hooks)",
)
def qos_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        for call in _walk_calls(mod):
            term = core.terminal_name(call.func)
            allowed = _QOS_CALLS.get(term)
            if allowed is None or mod.rel in allowed:
                continue
            findings.append(
                mod.finding(
                    "qos-plane",
                    call.lineno,
                    f"QoS construct {term}() outside its audited "
                    f"modules ({', '.join(sorted(allowed))}) — route "
                    "through presto_tpu.server.qos",
                )
            )
    return findings


# ----------------------------------------------------- result-cache plane

_RC = "server/result_cache.py"
_RC_COORD = {_RC, "server/coordinator.py"}

#: the serving-plane reuse tier's privileged constructs and their
#: audited callers: cache construction and the fingerprint×snapshot
#: key minting are reachable only from the coordinator (a second
#: cache, or a key minted elsewhere, would fork the freshness
#: contract); the MV rewrite seam only from server/result_cache.py
#: itself and the ONE planning seam in exec/local_runner.py
#: (plan_cached_keyed) — a rogue rewrite site could serve MV state a
#: base-table reader never opted into.
_RC_CALLS = {
    "ResultCache": _RC_COORD,
    "statement_key": _RC_COORD,
    "snapshot_vector": {_RC},
    "mview_rewrite": {_RC, "exec/local_runner.py"},
    "claim_refresh": _RC_COORD,
    "finish_refresh": _RC_COORD,
}


@core.register(
    "result-cache-plane",
    "result-cache construction, fingerprint×snapshot key minting, and "
    "the MV rewrite seam confined to server/result_cache.py + audited "
    "consumers (coordinator serving seam; local_runner planning seam)",
)
def result_cache_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        for call in _walk_calls(mod):
            term = core.terminal_name(call.func)
            allowed = _RC_CALLS.get(term)
            if allowed is None or mod.rel in allowed:
                continue
            findings.append(
                mod.finding(
                    "result-cache-plane",
                    call.lineno,
                    f"result-cache construct {term}() outside its "
                    f"audited modules ({', '.join(sorted(allowed))}) "
                    "— route through presto_tpu.server.result_cache",
                )
            )
    return findings


# ------------------------------------------------------------- reserve

_RESERVE_ALLOWED = {
    "utils/memory.py",
    "exec/staging.py",
    "exec/local_runner.py",
    "server/worker.py",
    "server/coordinator.py",
    # the exchange SPI accounts in-slice device pages and their
    # drain-materialized serialized twins under the producing task's
    # buffer key — the same owner the worker's HTTP shuffle buffers
    # use, released by the same DELETE/drop path
    "server/exchange_spi.py",
    # the serving-plane result cache byte-budgets its entries under
    # the pool's "result-cache" owner (non-blocking try_reserve only:
    # a cache fill must never stall or kill a query)
    "server/result_cache.py",
}


@core.register(
    "reserve-sites",
    "memory-pool construction and reservations confined to "
    "utils/memory.py + audited consumers (cluster accounting must be "
    "complete)",
)
def reserve_pass(modules: List[core.Module], src_dir: str):
    findings = []
    for mod in modules:
        if mod.rel in _RESERVE_ALLOWED:
            continue
        for call in _walk_calls(mod):
            term = core.terminal_name(call.func)
            if term == "MemoryPool":
                findings.append(
                    mod.finding(
                        "reserve-sites",
                        call.lineno,
                        "side-channel MemoryPool construction — the "
                        "cluster view cannot see it",
                    )
                )
            elif term in ("reserve", "try_reserve") and isinstance(
                call.func, ast.Attribute
            ):
                findings.append(
                    mod.finding(
                        "reserve-sites",
                        call.lineno,
                        f"ad-hoc .{term}() outside the audited "
                        "consumers",
                    )
                )
    return findings
