"""Compile-plane invariant checker: ``plan-params`` and
``history-sites``.

The zero-recompile serving plane (plan/canonical.py) and the
history-based statistics plane (plan/history.py) are only correct
while their privileged constructs stay confined:

- a ``RuntimeParam`` minted outside the canonicalizer bypasses the
  dtype/structure eligibility rules and miscompiles;
- a ``BoundParam`` minted outside it breaks the ordinal<->value
  correspondence the statement cache binds by;
- a compile-cache (``_compiled``) key assembled elsewhere can bake
  literals back in and re-open the compile-per-literal-variant hole;
- a history record, fingerprint, or ``lookup_rows`` call outside the
  store forks the canonical identity and the estimate provenance.

This is the AST successor of ``check_plan_params.py`` +
``check_history_sites.py``: calls are matched as calls (an
``isinstance(x, RuntimeParam)`` or a ``qs.plan_fingerprint`` attribute
read never needed an exemption to begin with), and the two legacy
read-only exemptions for ``_compiled`` (``len(self._compiled)``,
``self._runner._compiled``) are expressed structurally instead of by
line-scrubbing — a disallowed call sharing a line with an exempt read
still flags.
"""

from __future__ import annotations

import ast
from typing import List

from analysis import core

_CANONICAL = "plan/canonical.py"
_RUNNER = "exec/local_runner.py"
_HISTORY = "plan/history.py"

#: call-confinement rules: terminal callee name -> allowed modules
_PLAN_CALLS = {
    "RuntimeParam": {_CANONICAL, "plan/planner.py", "expr.py"},
    "BoundParam": {_CANONICAL, "sql/ast.py"},
    "hoist_params": {_CANONICAL, _RUNNER},
}

_HISTORY_CALLS = {
    "QueryHistoryStore": {_HISTORY, _RUNNER},
    "record_query": {_HISTORY, _RUNNER},
    "lookup_rows": {_HISTORY, "plan/optimizer.py"},
    "node_fingerprint": {
        _HISTORY,
        _RUNNER,
        "exec/explain.py",
        "server/coordinator.py",
    },
    "node_fingerprints": {
        _HISTORY,
        _RUNNER,
        "exec/explain.py",
        "server/coordinator.py",
    },
    "plan_fingerprint": {
        _HISTORY,
        _RUNNER,
        "exec/explain.py",
        "server/coordinator.py",
    },
}


def _exempt_compiled_reads(mod: core.Module) -> set:
    """ids of ``_compiled`` Attribute nodes that are read-only by
    structure: the direct argument of ``len()``, or reached through
    ``self._runner`` (a test/debug peek at the runner's cache)."""
    exempt = set()
    for node in mod.nodes:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Attribute)
            and node.args[0].attr == "_compiled"
        ):
            exempt.add(id(node.args[0]))
        elif isinstance(node, ast.Attribute) and node.attr == "_compiled":
            chain = core.dotted_name(node)
            if chain and chain.startswith("self._runner."):
                exempt.add(id(node))
    return exempt


def _confined_calls(modules, rules, rule_id, route_hint):
    findings = []
    for mod in modules:
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            term = core.terminal_name(node.func)
            allowed = rules.get(term)
            if allowed is None or mod.rel in allowed:
                continue
            findings.append(
                mod.finding(
                    rule_id,
                    node.lineno,
                    f"{term}() outside its audited modules "
                    f"({', '.join(sorted(allowed))}) — route through "
                    f"{route_hint}",
                )
            )
    return findings


@core.register(
    "plan-params",
    "literal hoisting, RuntimeParam/BoundParam construction, and "
    "compile-cache keying confined to plan/canonical.py + audited "
    "consumers",
)
def plan_params_pass(modules: List[core.Module], src_dir: str):
    findings = _confined_calls(
        modules, _PLAN_CALLS, "plan-params", "presto_tpu.plan.canonical"
    )
    for mod in modules:
        if mod.rel == _RUNNER:
            continue
        exempt = _exempt_compiled_reads(mod)
        for node in mod.nodes:
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "_compiled"
                and id(node) not in exempt
            ):
                findings.append(
                    mod.finding(
                        "plan-params",
                        node.lineno,
                        "_compiled store access outside "
                        "exec/local_runner.py — compile-cache keys "
                        "are built in exactly one place",
                    )
                )
    return findings


@core.register(
    "history-sites",
    "history records, canonical fingerprints, and estimate-time "
    "lookups confined to plan/history.py + audited consumers",
)
def history_sites_pass(modules: List[core.Module], src_dir: str):
    return _confined_calls(
        modules,
        _HISTORY_CALLS,
        "history-sites",
        "presto_tpu.plan.history",
    )


# ------------------------------------------------------- serving batch

_COORDINATOR = "server/coordinator.py"

#: the micro-batch serving plane is only correct while its privileged
#: constructs stay confined: batch-axis stacking / the vmapped compile
#: entry in plan/canonical.py (a stacking built elsewhere can disagree
#: with the hoisting eligibility rules and CROSS members' answers, not
#: just miss a cache), the batched executor in its one audited caller,
#: and batch-queue key construction in server/coordinator.py (a queue
#: key minted elsewhere could group statements that do not share a
#: compiled program)
_BATCH_CALLS = {
    "stack_param_vectors": {_CANONICAL, _RUNNER},
    "vmap_program": {_CANONICAL, _RUNNER},
    "batch_entry_key": {_CANONICAL, _RUNNER},
    "batch_lanes": {_CANONICAL, _RUNNER},
    "execute_plan_microbatch": {_RUNNER, _COORDINATOR},
    "compact_page_window": {"page.py", _RUNNER},
    "MicrobatchQueue": {_COORDINATOR},
    "_microbatch_key": {_COORDINATOR},
}


# ------------------------------------------------------ exchange plane

_EXCHANGE = "parallel/exchange.py"
_EXCHANGE_SPI = "server/exchange_spi.py"
_SCHEDULER = "server/scheduler.py"
_WORKER = "server/worker.py"

#: the ICI-native shuffle is only correct while its privileged
#: constructs stay confined: device collectives and the exchange
#: kernels in parallel/exchange.py (a bucket hash built elsewhere can
#: silently disagree with the host wire hash and lose rows across
#: partitions on a mixed-transport retry), the segment + emit/fetch
#: surface in server/exchange_spi.py with the worker as its one
#: audited consumer, and transport SELECTION in the scheduler (a
#: transport chosen ad hoc can put an ICI edge across slices, where
#: the segment cannot serve it)
_EXCHANGE_CALLS = {
    "all_to_all": {_EXCHANGE},
    "all_gather": {_EXCHANGE},
    "shard_map": {_EXCHANGE, "parallel/distributed_runner.py"},
    "bucket_dest": {_EXCHANGE, _EXCHANGE_SPI},
    "ici_append": {_EXCHANGE, _EXCHANGE_SPI},
    "ici_partition_counts": {_EXCHANGE, _EXCHANGE_SPI},
    "wire_crc_table": {_EXCHANGE, _EXCHANGE_SPI},
    "partition_exchange": {_EXCHANGE, "parallel/distributed_runner.py"},
    # single-program collective kernels: constructed in
    # parallel/exchange.py, driven only by the exchange SPI
    "collective_counts": {_EXCHANGE, _EXCHANGE_SPI},
    "collective_gather": {_EXCHANGE, _EXCHANGE_SPI},
    "collective_take": {_EXCHANGE, _EXCHANGE_SPI},
    "IciSegment": {_EXCHANGE_SPI},
    "emit_partitioned": {_EXCHANGE_SPI, _WORKER},
    "emit_gather": {_EXCHANGE_SPI, _WORKER},
    "ici_fetch": {_EXCHANGE_SPI, _WORKER},
    "device_merge": {_EXCHANGE_SPI, _WORKER},
    "collective_merge": {_EXCHANGE_SPI, _WORKER},
    "collective_payloads": {_EXCHANGE_SPI, _WORKER},
    "ici_batches_to_payloads": {_EXCHANGE_SPI, _WORKER},
    "serialize_ici_frames": {_EXCHANGE_SPI, _WORKER},
    "buffer_frames": {_EXCHANGE_SPI, _WORKER},
    # the coordinator's half of the ICI gather edge
    "ici_gather": {_EXCHANGE_SPI, "server/coordinator.py"},
    "select_exchange_transport": {_SCHEDULER, "server/coordinator.py"},
    "select_exchange_edges": {_SCHEDULER, "server/coordinator.py"},
}


@core.register(
    "exchange-plane",
    "collective construction and ICI exchange kernels confined to "
    "parallel/exchange.py, the segment/emit/fetch surface to "
    "server/exchange_spi.py (+ the worker), transport selection to "
    "the scheduler",
)
def exchange_plane_pass(modules: List[core.Module], src_dir: str):
    return _confined_calls(
        modules,
        _EXCHANGE_CALLS,
        "exchange-plane",
        "presto_tpu.parallel.exchange / "
        "presto_tpu.server.exchange_spi / the scheduler",
    )


# ----------------------------------------------------- adaptive plane

_DYNFILTER = "exec/dynfilter.py"
_OPTIMIZER = "plan/optimizer.py"

#: the adaptive-execution plane is only correct while its privileged
#: constructs stay confined: epoch reads/bumps and the shared
#: divergence test live in plan/history.py (an epoch minted elsewhere
#: would desynchronize every staleness judgement), the statement-cache
#: replan seam in plan/canonical.py with the runner as its one audited
#: consumer (a replan decided elsewhere could serve a plan whose
#: consulted evidence was never captured), and runtime strategy-switch
#: construction in the coordinator + exec/dynfilter.py (a switch built
#: elsewhere could bypass the fail-open discipline and turn a wrong
#: estimate into a failed query)
_ADAPTIVE_CALLS = {
    # epoch plane: reads confined to history + the replan seam
    "epoch_of": {_HISTORY, _CANONICAL},
    "learned_rows": {_HISTORY, _CANONICAL},
    # the ONE divergence test both layers share
    "diverged": {_HISTORY, _CANONICAL, _DYNFILTER, _COORDINATOR},
    # consult capture: the runner wraps canonical planning in it;
    # the optimizer notes the classic fallback estimate
    "capture_consults": {_HISTORY, _RUNNER},
    "note_estimate": {_HISTORY, _OPTIMIZER},
    "with_overrides": {_HISTORY, _COORDINATOR},
    # the replan seam and its audited consumer
    "stale_consults": {_CANONICAL, _RUNNER},
    "_adaptive_replan": {_RUNNER},
    # runtime strategy-switch construction
    "_adaptive_maybe_switch": {_COORDINATOR},
    "_adaptive_probe_build": {_COORDINATOR},
    "_adaptive_nparts": {_COORDINATOR},
    "_adaptive_note": {_COORDINATOR},
}


@core.register(
    "adaptive-plane",
    "adaptive-execution constructs confined: epoch reads/bumps and "
    "the divergence test to plan/history.py, the replan seam to "
    "plan/canonical.py (+ the runner), strategy-switch construction "
    "to the coordinator and exec/dynfilter.py",
)
def adaptive_plane_pass(modules: List[core.Module], src_dir: str):
    return _confined_calls(
        modules,
        _ADAPTIVE_CALLS,
        "adaptive-plane",
        "presto_tpu.plan.history / presto_tpu.plan.canonical / the "
        "coordinator's adaptive seam",
    )


@core.register(
    "serving-batch",
    "micro-batch constructs confined: batch-axis stacking and vmap "
    "entries to plan/canonical.py, batch-queue keys to "
    "server/coordinator.py",
)
def serving_batch_pass(modules: List[core.Module], src_dir: str):
    findings = _confined_calls(
        modules,
        _BATCH_CALLS,
        "serving-batch",
        "presto_tpu.plan.canonical / the coordinator batch queue",
    )
    # raw vmap anywhere outside the canonicalizer is a batch-axis
    # construction site by definition
    for mod in modules:
        if mod.rel == _CANONICAL:
            continue
        for node in mod.nodes:
            if (
                isinstance(node, ast.Call)
                and core.terminal_name(node.func) == "vmap"
            ):
                findings.append(
                    mod.finding(
                        "serving-batch",
                        node.lineno,
                        "raw vmap — batched program entries are "
                        "constructed only by plan/canonical.py "
                        "(vmap_program)",
                    )
                )
    return findings


_TELEMETRY = "utils/telemetry.py"
_DEVICEDIAG = "utils/devicediag.py"
_STAGING = "exec/staging.py"

#: the device-plane numbers are only trustworthy while their
#: increment sites stay the audited choke points: a rogue
#: ``count_dispatch`` in a connector would double-count the plane the
#: ROADMAP's "dispatch counts visibly down" is judged by, a second
#: DeviceTelemetry instance would fork the counters the bench diffs,
#: and a sampler/federation constructed outside the coordinator would
#: sample a registry no system table serves. (bench.py and tests are
#: outside the analyzed tree; they consume snapshots, not counters.)
_TELEMETRY_CALLS = {
    # the ONE instance lives in utils/telemetry.py (module singleton)
    "DeviceTelemetry": {_TELEMETRY},
    # federation/sampler construction: the coordinator's boot seam
    "MetricsFederation": {_TELEMETRY, _COORDINATOR},
    "MetricsSampler": {_TELEMETRY, _COORDINATOR},
    # increment choke points (the exchange SPI counts its collective
    # and gather dispatches through the same audited name)
    "count_dispatch": {_TELEMETRY, _RUNNER, _EXCHANGE_SPI},
    "count_compile": {_TELEMETRY, _RUNNER},
    "count_h2d": {_TELEMETRY, _STAGING},
    "count_d2h": {_TELEMETRY, _RUNNER, _STAGING, _EXCHANGE_SPI},
    "count_padding": {_TELEMETRY, _RUNNER, _STAGING},
    # per-query attribution fold: the runner's locked seam
    "_fold_device_stat": {_RUNNER},
    # structured diagnosis: probes from the worker boot seam only
    # (the bench rides the same helper from outside the tree);
    # recording is the probe's own epilogue
    "probe_backend": {_DEVICEDIAG, _WORKER},
    "record_diag": {_DEVICEDIAG},
    # the history-derived progress denominator: kept inside
    # plan/history.py (the lookup_rows confinement) with the
    # coordinator as its one consumer
    "progress_total_rows": {_HISTORY, _COORDINATOR},
}


@core.register(
    "telemetry-plane",
    "device-telemetry constructs confined: counter increments to the "
    "runner/staging/exchange choke points, sampler+federation "
    "construction to the coordinator, probes to the worker boot seam",
)
def telemetry_plane_pass(modules: List[core.Module], src_dir: str):
    return _confined_calls(
        modules,
        _TELEMETRY_CALLS,
        "telemetry-plane",
        "presto_tpu.utils.telemetry (DEVICE) / the coordinator's "
        "telemetry seam",
    )
