"""``metric-names`` pass: every metric name registers under exactly
one kind (counter vs timer vs distribution) across the whole tree.

The registry raises TypeError at runtime on a kind conflict, but only
on the code path that hits it; this pass fails the conflict at
analysis time instead. AST successor of ``check_metric_names.py``,
with one real upgrade: registration through a *loop variable* over a
literal tuple/list resolves to the literal names —

    for m in ("pool.scale_up", "pool.scale_down"):
        REGISTRY.counter(m)

registers both names (the regex predecessor saw no string literal in
the call and silently skipped the PR 7-9 counter families registered
this way: history.*, journal.*, pool.*, memory.*, spill.*).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from analysis import core

_KINDS = {"counter", "timer", "distribution"}


def collect_sites(
    modules: List[core.Module],
) -> Dict[str, Set[Tuple[str, str, int]]]:
    """metric name -> {(kind, rel, line), ...} over every
    ``REGISTRY.<kind>(...)`` site, resolving literal arguments,
    literals anywhere inside the argument expressions (conditional
    names), and loop variables bound over literal sequences."""
    sites: Dict[str, Set[Tuple[str, str, int]]] = {}
    for mod in modules:
        #: Name id -> literal strings it loops over (innermost wins is
        #: unnecessary: names are merged — a conflict is a conflict)
        loop_bindings: Dict[str, List[str]] = {}
        for node in mod.nodes:
            if isinstance(node, ast.For) and isinstance(
                node.target, ast.Name
            ):
                if isinstance(node.iter, (ast.Tuple, ast.List)):
                    vals = [
                        e.value
                        for e in node.iter.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
                    if vals:
                        loop_bindings.setdefault(
                            node.target.id, []
                        ).extend(vals)
        for node in mod.nodes:
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KINDS
                and core.terminal_name(node.func.value) == "REGISTRY"
            ):
                continue
            kind = node.func.attr
            names: List[str] = []
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                names.extend(core.str_constants(arg))
                if isinstance(arg, ast.Name):
                    names.extend(loop_bindings.get(arg.id, ()))
            for name in names:
                sites.setdefault(name, set()).add(
                    (kind, mod.rel, node.lineno)
                )
    return sites


def find_conflicts(sites):
    out = []
    for name, entries in sorted(sites.items()):
        kinds = {k for k, _rel, _line in entries}
        if len(kinds) > 1:
            out.append((name, sorted(entries)))
    return out


#: metric-family confinement: families whose names may register only
#: in their owning modules. The ``device.*`` counters ARE the
#: device-plane numbers the bench diffs and EXPLAIN ANALYZE renders —
#: a stray registration elsewhere would fork a family the dashboards
#: treat as one stream; ``telemetry.*`` is the plane's own
#: bookkeeping (scrape failures, sample counts).
FAMILY_CONFINEMENT = {
    "device.": {"utils/telemetry.py", "utils/devicediag.py"},
    "telemetry.": {"utils/telemetry.py"},
}


def find_family_violations(sites):
    """Registrations of a confined family outside its owning modules:
    ``[(name, rel, line, allowed), ...]``."""
    out = []
    for name, entries in sorted(sites.items()):
        for prefix, allowed in FAMILY_CONFINEMENT.items():
            if not name.startswith(prefix):
                continue
            for _kind, rel, line in sorted(entries):
                if rel not in allowed:
                    out.append((name, rel, line, allowed))
    return out


@core.register(
    "metric-names",
    "every metric name registers under ONE kind "
    "(counter/timer/distribution), loop-registered families included; "
    "device.*/telemetry.* families register only in their owning "
    "modules",
)
def metric_names_pass(modules: List[core.Module], src_dir: str):
    by_rel = {m.rel: m for m in modules}
    findings = []
    sites = collect_sites(modules)
    for name, entries in find_conflicts(sites):
        kind0, rel0, line0 = entries[0]
        mod = by_rel[rel0]
        where = ", ".join(
            f"{k} at {rel}:{line}" for k, rel, line in entries
        )
        findings.append(
            mod.finding(
                "metric-names",
                line0,
                f"metric {name!r} registered under conflicting kinds: "
                f"{where}",
            )
        )
    for name, rel, line, allowed in find_family_violations(sites):
        findings.append(
            by_rel[rel].finding(
                "metric-names",
                line,
                f"metric {name!r} registers outside its family's "
                f"owning modules ({', '.join(sorted(allowed))}) — "
                "the device/telemetry planes must stay one stream",
            )
        )
    return findings
