"""Unified AST analysis framework for the presto-tpu tree.

One loader, one pass registry, one finding type, one CLI
(``tools/analyze.py``). The passes:

====================  =================================================
rule id               enforces
====================  =================================================
lock-order            held-while-acquiring lock graph stays acyclic
                      (static deadlock detection)
blocking-under-lock   no RPC / DMA / file I/O / sleep / unbounded join
                      / journal-spool write while a lock is held
plan-params           compile-plane constructs confined to
                      plan/canonical.py + audited consumers
history-sites         history-plane constructs confined to
                      plan/history.py + audited consumers
serving-batch         micro-batch constructs confined: batch-axis
                      stacking/vmap entries to plan/canonical.py,
                      batch-queue keys to server/coordinator.py
rpc-confinement       raw urlopen confined to server/rpc.py
staging-confinement   device_put / boundary jnp conversions confined
                      to exec/staging.py
dynfilter-confinement filter summaries confined to exec/dynfilter.py
attempt-ids           task-id mint/parse confined to server/task_ids.py
journal-sites         journal frames/API confined to server/journal.py
                      + audited consumers
reserve-sites         pool reservations confined to utils/memory.py +
                      audited consumers
metric-names          one kind per metric name
====================  =================================================

Suppression: a trailing ``# lint: disable=<rule>[,<rule>]`` on the
finding's line. Blocking-under-lock additionally honors the audited
allowlist in :mod:`analysis.allowlist` (one-line justification per
entry). ``parse-error`` findings (unparseable files) always fail.
"""

from analysis.core import (  # noqa: F401
    Finding,
    Module,
    PASSES,
    all_rules,
    load_baseline,
    load_modules,
    register,
    run_passes,
    to_json,
    write_baseline,
)
