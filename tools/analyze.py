#!/usr/bin/env python
"""One CLI for the unified AST analysis framework.

Usage::

    python tools/analyze.py [src_dir] [options]

    --rules a,b,c         run only these passes (default: all)
    --list-rules          print every rule id + description and exit
    --json                stable, diffable JSON report on stdout
    --baseline FILE       demote findings listed in FILE to warn-only
    --write-baseline FILE write the current unsuppressed findings as a
                          baseline (introduce a new pass warn-only,
                          enforce once the tree is clean)

Exit code 0 when every finding is suppressed (inline
``# lint: disable=<rule>``), allowlisted (analysis/allowlist.py), or
baselined; 1 otherwise. ``src_dir`` defaults to the repo's
``presto_tpu`` package.

Wired into the test suite via tests/test_static_analysis.py — the one
entrypoint that replaced the per-suite lint wiring.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import analysis  # noqa: E402


def _default_src() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu",
    )


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    src_dir = _default_src()
    rules = None
    as_json = False
    baseline_path = None
    write_baseline_path = None
    i = 0
    positional = []
    while i < len(args):
        a = args[i]
        if a == "--json":
            as_json = True
        elif a == "--list-rules":
            for rule in analysis.all_rules():
                print(f"{rule:<22} {analysis.PASSES[rule].doc}")
            return 0
        elif a == "--rules":
            i += 1
            rules = [r.strip() for r in args[i].split(",") if r.strip()]
        elif a == "--baseline":
            i += 1
            baseline_path = args[i]
        elif a == "--write-baseline":
            i += 1
            write_baseline_path = args[i]
        elif a.startswith("--"):
            print(f"unknown option {a}", file=sys.stderr)
            return 2
        else:
            positional.append(a)
        i += 1
    if positional:
        src_dir = positional[0]

    baseline = None
    if baseline_path and os.path.exists(baseline_path):
        baseline = analysis.load_baseline(baseline_path)

    findings = analysis.run_passes(src_dir, rules=rules, baseline=baseline)

    if write_baseline_path:
        analysis.write_baseline(write_baseline_path, findings)

    active = [f for f in findings if f.active]
    if as_json:
        print(analysis.to_json(findings, src_dir))
        return 1 if active else 0

    for f in findings:
        tag = ""
        if f.suppressed:
            tag = " [suppressed]"
        elif f.allowlisted:
            tag = f" [allowlisted: {f.justification}]"
        elif f.baselined:
            tag = " [baselined]"
        print(f"{f.path}:{f.line}: [{f.rule}] {f.message}{tag}")
        if f.snippet:
            print(f"    {f.snippet}")
    ran = rules or analysis.all_rules()
    if not active:
        quiet = len(findings) - len(active)
        extra = f" ({quiet} suppressed/allowlisted/baselined)" if (
            quiet
        ) else ""
        print(
            f"analyze: {len(ran)} pass(es) clean over {src_dir}{extra}"
        )
        return 0
    print(
        f"analyze: {len(active)} finding(s) across "
        f"{len({f.rel for f in active})} file(s)"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
