"""Per-phase profile of the benchmark path (TPC-H Q1 @ SF1).

Breaks one steady-state `bench.py` iteration into its host/device
components so BASELINE.md can carry a real device-vs-host time split
(SURVEY.md §5.1; VERDICT r2 item 1):

  bind+prune    host Python: _bind_params + prune_columns per call
  fingerprint   host Python: compiled-program cache key
  dispatch      jax dispatch of the jitted program (async, no sync)
  device        block_until_ready on the outputs (true device time +
                transfer, measured after dispatch returned)
  ctl_fetch     device_get of the control outputs (flags/errors)
  host_ops      host root stage (numpy sort over gathered rows)
  e2e           full runner.execute_plan, for cross-checking

Optionally writes a jax.profiler trace (--trace DIR) for XProf.

Usage:  python tools/profile_q1.py [--sf sf1] [--iters 5] [--trace DIR]
"""

import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", default="sf1")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--trace", default=None)
    ap.add_argument("--platform", default=None,
                    help="force jax platform (e.g. cpu)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import __graft_entry__ as G
    from presto_tpu.exec.host_ops import apply_host_ops, peel_host_ops
    from presto_tpu.exec.local_runner import LocalQueryRunner
    from presto_tpu.plan.optimizer import prune_columns
    from presto_tpu.plan import nodes as N
    from presto_tpu.plan.planner import plan_statement
    from presto_tpu.sql import parse_statement

    print("devices:", jax.devices())
    runner = LocalQueryRunner()
    sql = G._Q1.replace("tiny", args.sf)
    stmt = parse_statement(sql)
    plan = plan_statement(stmt, runner.catalogs, runner.session)

    # warm: stage tables + compile
    t0 = time.perf_counter()
    runner.execute_plan(plan)
    print(f"cold run (stage+compile): {time.perf_counter() - t0:.3f}s")
    t0 = time.perf_counter()
    runner.execute_plan(plan)
    print(f"warm run: {time.perf_counter() - t0:.3f}s")

    # per-phase breakdown of what execute_plan does
    phases = {k: [] for k in (
        "bind_prune", "fingerprint", "dispatch", "device", "ctl_fetch",
        "host_ops", "e2e")}
    for _ in range(args.iters):
        t0 = time.perf_counter()
        root = runner._bind_params(plan)
        root = prune_columns(root)
        host_ops = []
        if runner.session.get("host_root_stage"):
            root, host_ops = peel_host_ops(root)
        phases["bind_prune"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        fp = root.fingerprint()
        phases["fingerprint"].append(time.perf_counter() - t0)

        scans = [n for n in N.walk(root) if isinstance(n, N.TableScanNode)]
        pages = [runner._load_table(s) for s in scans]
        offload = runner.session.get("tpu_offload")
        entry = runner._compiled.get((fp, False, offload))
        if entry is None:
            sys.exit(
                "no compiled whole-plan program for this root (the plan "
                "took the streamed path, which this per-phase breakdown "
                "does not cover) — use a resident scale factor"
            )
        fn, msgs_cell, _ = entry

        t0 = time.perf_counter()
        with runner._device_scope():
            page, flags_arr, err_arr, cnt_arr = fn(pages)
        phases["dispatch"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        jax.block_until_ready((page, flags_arr, err_arr))
        phases["device"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        jax.device_get([flags_arr, err_arr, cnt_arr])
        phases["ctl_fetch"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        if host_ops:
            apply_host_ops(page, host_ops)
        phases["host_ops"].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        runner.execute_plan(plan)
        phases["e2e"].append(time.perf_counter() - t0)

    print(f"\n{'phase':<12} {'best':>9} {'median':>9}")
    for k, v in phases.items():
        print(f"{k:<12} {min(v) * 1e3:>8.1f}ms {statistics.median(v) * 1e3:>8.1f}ms")
    summed = sum(min(phases[k]) for k in phases if k != "e2e")
    print(f"{'sum(parts)':<12} {summed * 1e3:>8.1f}ms")

    if args.trace:
        with jax.profiler.trace(args.trace):
            for _ in range(3):
                runner.execute_plan(plan)
        print("trace written to", args.trace)


if __name__ == "__main__":
    main()
